"""Cluster-scope prefix cache: directory + fault-tolerant adoption.

The radix prefix index (inference/cache.RadixIndex) is replica-LOCAL:
a replica that already paid prefill for a shared prompt head helps its
own later requests, but a peer replica — or a cold replica that just
joined — pays the whole prefill again.  This module makes the prefix
plane CLUSTER-scope (the DistServe/Splitwise shape on ROADMAP item 2):

  * ``chunk_keys``      — rolling content hash per block-sized prompt
    chunk; key ``i`` identifies the whole prefix through chunk ``i``,
    so one lookup finds the longest published prefix of a prompt.
  * ``PrefixDirectory`` — prompt-chunk-hash → {holder replica, block
    ids, generation}, LRU-bounded like the local RadixIndex.  Pure
    bookkeeping, jax-free (the head hosts one for multi-node fleets —
    core/head.py ``_h_prefix_publish``/``_h_prefix_lookup``/
    ``_h_prefix_invalidate`` speak the wire vocabulary over the
    existing envelope plane).
  * ``PrefixPlane``     — the per-fleet orchestrator: publishes what
    replicas' engines report after prefill, hints the router toward a
    directory-confirmed holder (prefix-affinity routing), and — on a
    directory hit on a NON-holder replica — fetches the K/V block
    bytes from the holder and installs them into the adopter's radix
    index under the normal CoW/refcount rules, so the very next
    admission adopts them like any locally-cached prefix.

The robustness contract (the reason this rides the fault plane): every
failure — lookup raced an invalidation, holder died mid-fetch, stale
pool generation, block pressure at the receiver — downgrades SILENTLY
to the chunked-prefill recompute the engine would have run anyway.
``adopt()`` never raises into the request path; disabling the
directory (or injecting 100% fetch failure at the ``prefix_fetch``
chaos point) reproduces replica-local behavior byte-identically.

Invalidation rules (who may serve what):

  * replica killed (``Fleet.kill_replica`` / route-time dead-mark) →
    ``invalidate_holder`` drops every entry it published.
  * replica DRAINING (``DeploymentState.drain_replicas``) → same, and
    the router's affinity hint skips a draining holder IMMEDIATELY via
    its lifecycle — never via a dead-mark whose DEAD_TTL_S expiry
    would resurrect it.
  * holder pool reset (donated-buffer recovery) → the pool GENERATION
    bumps; ``prefix_extract`` rejects the stale generation with the
    typed error and the plane purges that generation's entries — a
    recovered pool's old block ids are never served.

Chaos points (``FaultPlan.on_infer``): ``prefix_dir_lookup``,
``prefix_fetch``, ``prefix_install`` — scripted fns may raise (inject
the failure) or kill/drain the holder mid-adoption (ctx carries the
holder handle); the gate discipline is the house standard (one global
load + ``is None`` branch when disarmed, enforced by ``ray_tpu lint``
via analysis/hotpath_registry.py).
"""

from __future__ import annotations

import hashlib
import itertools
import threading
from collections import OrderedDict
from typing import Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.serve.qos import (PrefixInstallPressure, PrefixTransferError,
                               PrefixUnavailable, StalePrefixGeneration)

__all__ = [
    "chunk_keys", "PrefixDirectory", "PrefixPlane",
    "PrefixTransferError", "StalePrefixGeneration", "PrefixUnavailable",
    "PrefixInstallPressure",
]


def chunk_keys(tokens, block_size: int) -> list:
    """Rolling chunk-hash chain for a token sequence: one hex key per
    FULL block, where key ``i`` digests everything through chunk ``i``
    — so equal keys mean equal whole prefixes, and the longest match
    is found by walking a prompt's own key list back to front.  Only
    full blocks are published/looked up (partial tails are written by
    decode and never shared — the same rule the local RadixIndex
    publication follows)."""
    bs = int(block_size)
    if bs < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    h = hashlib.blake2b(digest_size=16)
    keys = []
    for i in range(len(tokens) // bs):
        chunk = tokens[i * bs:(i + 1) * bs]
        h.update(b"".join(
            int(t).to_bytes(8, "little", signed=True) for t in chunk))
        keys.append(h.copy().hexdigest())
    return keys


class PrefixDirectory:
    """Prompt-chunk-hash → {holder, block ids, generation} with LRU
    eviction — the cluster-scope analogue of the replica-local radix
    index.  Thread-safe (fleet pool threads publish/lookup/invalidate
    concurrently; the head's event loop is single-threaded but shares
    the class).  The directory is ADVISORY: extraction re-validates
    against the holder's live trie and pool generation, so a stale
    entry costs one failed fetch, never wrong bytes."""

    def __init__(self, capacity: int = 4096):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        # key -> {"holder", "node", "generation", "n_tokens", "blocks"}
        self._entries: "OrderedDict[str, dict]" = OrderedDict()
        self.published = 0
        self.hits = 0
        self.misses = 0
        self.invalidated = 0
        self.evicted = 0

    def publish(self, keys: list, *, holder: str, n_tokens: int,
                generation: int, block_size: int, node: str = "",
                blocks: tuple = (), engine: str = "") -> int:
        """Register one prefix chain: ``keys[i]`` covers the first
        ``(i + 1) * block_size`` tokens.  Later publishes of the same
        key overwrite (freshest holder/generation wins).  ``engine`` is
        the holder's conduit address (the engine-registry name the node
        plane's ``block_fetch`` resolves — empty for in-proc-only
        topologies).  Returns the number of entries registered."""
        bs = int(block_size)
        n = 0
        with self._lock:
            for i, key in enumerate(keys):
                covered = (i + 1) * bs
                if covered > int(n_tokens):
                    break
                self._entries[key] = {
                    "holder": holder, "node": node,
                    "generation": int(generation),
                    "n_tokens": covered,
                    "blocks": tuple(blocks[:i + 1]),
                    "engine": engine,
                }
                self._entries.move_to_end(key)
                n += 1
            self.published += n
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evicted += 1
        return n

    def lookup(self, keys: list) -> Optional[dict]:
        """Longest published prefix of the chain ``keys`` (walked back
        to front).  Returns a COPY of the entry + its key, or None."""
        with self._lock:
            for key in reversed(keys):
                e = self._entries.get(key)
                if e is not None:
                    self._entries.move_to_end(key)
                    self.hits += 1
                    return {"key": key, **e}
            self.misses += 1
            return None

    def purge(self, key: str) -> bool:
        with self._lock:
            if self._entries.pop(key, None) is not None:
                self.invalidated += 1
                return True
            return False

    def invalidate_holder(self, holder: str) -> int:
        """Drop every entry a replica published (death / drain)."""
        return self._invalidate(lambda e: e["holder"] == holder)

    def invalidate_node(self, node: str) -> int:
        """Drop every entry hosted on a node (node death / drain — the
        head's ``_node_dead``/``_begin_node_drain`` hook)."""
        return self._invalidate(lambda e: e["node"] == node)

    def invalidate_stale(self, holder: str, stale_generation: int) -> int:
        """Drop a holder's entries at (or before) a generation its pool
        reset has invalidated — the donated-pool recovery rule: a reset
        pool's old block ids must never be served."""
        g = int(stale_generation)
        return self._invalidate(
            lambda e: e["holder"] == holder and e["generation"] <= g)

    def _invalidate(self, pred) -> int:
        with self._lock:
            drop = [k for k, e in self._entries.items() if pred(e)]
            for k in drop:
                del self._entries[k]
            self.invalidated += len(drop)
            return len(drop)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "capacity": self.capacity,
                "published": self.published,
                "hits": self.hits,
                "misses": self.misses,
                "invalidated": self.invalidated,
                "evicted": self.evicted,
            }


class PrefixPlane:
    """Per-fleet adoption orchestrator: directory + publish + affinity
    hint + the fetch/install path, with the total-fallback contract.

    Installed by ``Fleet`` when ``FleetConfig.cluster_prefix`` is on;
    ``None`` otherwise — every call site gates on that, so the default
    fleet path is byte-identical with the plane absent."""

    def __init__(self, fleet, *, capacity: int = 4096,
                 fetch_timeout_s: float = 5.0):
        self.fleet = fleet
        self.directory = PrefixDirectory(capacity=capacity)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self._lock = threading.Lock()
        self._block_size: Optional[int] = None
        # (replica_tag, key) pairs known adopted/held — a cheap memo so
        # a hot shared prefix is fetched ONCE per replica, not once per
        # request.  Never consulted for correctness: a pool reset on
        # the adopter just means the next admission recomputes locally.
        self._adopted: set = set()
        self._adopt_seq = itertools.count(1)
        # the three ISSUE counters (merged into fleet_snapshot and the
        # serve_fleet_prefix_* /metrics series)
        self.remote_hits = 0
        self.remote_fetch_failures = 0
        self.fallback_recomputes = 0

    # ------------------------------------------------------------- chaos

    def _chaos(self, point: str, **ctx) -> Optional[dict]:
        """Fault-plane hook (prefix_dir_lookup / prefix_fetch /
        prefix_install): zero-overhead gate when no plan is installed.
        Returns the ctx when a plan ran (a scripted fn may have mutated
        it or killed/drained the holder it carries)."""
        fi = _fi._active
        if fi is None:
            return None
        ctx["deployment"] = self.fleet.name
        fi.on_infer(point, ctx)
        return ctx

    def _count(self, field_name: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field_name, getattr(self, field_name) + n)

    def counters(self) -> dict:
        with self._lock:
            return {
                "prefix_remote_hits": self.remote_hits,
                "prefix_remote_fetch_failures": self.remote_fetch_failures,
                "prefix_fallback_recomputes": self.fallback_recomputes,
                "prefix_directory_entries": len(self.directory),
            }

    def _keys(self, model, tokens) -> list:
        """Directory keys are MODEL-scoped: two multiplexed variants
        sharing a token prefix hold different K/V, so the model id is
        folded into every chunk key."""
        with self._lock:
            bs = self._block_size
        if bs is None:
            return []
        return [f"{model or ''}|{k}" for k in chunk_keys(tokens, bs)]

    # ----------------------------------------------------------- publish

    def publish_from(self, replica) -> int:
        """Drain a replica's prefix outbox (what its engines published
        to their local radix index since last drain) into the
        directory.  Best-effort: a dead/drained body publishes
        nothing."""
        try:
            exports = self._body_call(replica, "prefix_export", ())
        except Exception:
            return 0
        n = 0
        for ex in exports or ():
            tokens = ex.get("tokens") or ()
            bs = int(ex.get("block_size", 0))
            if not tokens or bs < 1:
                continue
            with self._lock:
                if self._block_size is None:
                    self._block_size = bs
                elif self._block_size != bs:
                    continue     # mixed-geometry fleet: only one plane
            keys = self._keys(ex.get("model"), tokens)
            gen = int(ex.get("generation", 0))
            eng = ex.get("engine") or ""
            n += self.directory.publish(
                keys, holder=replica.tag, n_tokens=len(tokens),
                generation=gen, block_size=bs,
                blocks=tuple(ex.get("blocks") or ()), engine=eng)
            with self._lock:
                for key in keys:
                    self._adopted.add((replica.tag, key))
            # mirror to the head-registered directory so OTHER fleet
            # processes (multi-node serving) can find this prefix; the
            # local node proxies the message head-ward (standalone
            # nodes answer it as a benign no-op)
            self._head_send({"t": "prefix_publish", "keys": keys,
                             "holder": replica.tag,
                             "n_tokens": len(tokens), "generation": gen,
                             "block_size": bs, "engine": eng})
        return n

    def invalidate_holder(self, tag: str) -> int:
        """Replica left the serving set (killed / draining / torn
        down): its entries must stop routing fetches at it.  This fleet
        OWNS its replica tags, so the drop mirrors to the head
        directory too (a foreign fleet's holders are never ours to
        invalidate)."""
        self._head_send({"t": "prefix_invalidate", "holder": tag})
        return self.directory.invalidate_holder(tag)

    # ------------------------------------------------------------ lookup

    def _req_model(self, args: tuple):
        req = args[0] if args and isinstance(args[0], dict) else None
        return req.get("model") if req is not None else None

    def _prompt_tokens(self, args: tuple) -> Optional[list]:
        """Token-id prompt out of a request envelope; None when there
        is nothing hashable (string prompts would need the replica's
        vocab to encode — they simply skip the cluster plane and take
        the local path)."""
        req = args[0] if args and isinstance(args[0], dict) else None
        if req is None:
            return None
        prompt = req.get("prompt")
        if not isinstance(prompt, (list, tuple)) or not prompt:
            return None
        try:
            return [int(t) for t in prompt]
        except (TypeError, ValueError):
            return None

    def _lookup(self, tokens: list, model=None) -> Optional[dict]:
        with self._lock:
            bs = self._block_size
        if bs is None or len(tokens) <= bs:
            return None
        # the last prompt token always runs prefill (its logits sample
        # the first output token — the RadixIndex cap), so never look
        # up the full prompt's chain
        keys = self._keys(model, tokens[:len(tokens) - 1])
        if not keys:
            return None
        try:
            self._chaos("prefix_dir_lookup", keys=len(keys))
        except BaseException:
            return None          # injected lookup failure: local path
        hit = self.directory.lookup(keys)
        if hit is None:
            # this fleet never published it — ask the head-registered
            # directory (a sibling fleet process may have).  Remote
            # hits carry no routable replica handle; adoption then
            # goes through the node block-fetch conduit.
            hit = self._head_lookup(keys)
            if hit is not None:
                hit["remote"] = True
        if hit is not None:
            hit["block_size"] = bs
        return hit

    def route_hint(self, args: tuple) -> Optional[str]:
        """Directory-confirmed holder tag for this request's prompt —
        the router's prefix-affinity preference.  Advisory only: the
        router re-checks lifecycle/occupancy and falls through to p2c
        when the holder is saturated, draining or dead."""
        tokens = self._prompt_tokens(args)
        if tokens is None:
            return None
        hit = self._lookup(tokens, self._req_model(args))
        return hit["holder"] if hit is not None else None

    # ----------------------------------------------------------- adoption

    def before_call(self, replica, args: tuple) -> None:
        """The adoption choke point (Fleet._call runs it before every
        replica call when the plane is enabled): on a directory hit
        whose holder is NOT the serving replica, fetch the K/V block
        bytes from the holder and install them into the adopter's
        radix index, so the engine's normal admission match adopts them
        with the usual CoW/refcount rules.  TOTAL fallback: every
        failure is counted, noted, and swallowed — the request then
        recomputes its prefill locally, exactly as if the plane did
        not exist."""
        tokens = self._prompt_tokens(args)
        if tokens is None:
            return
        model = self._req_model(args)
        hit = self._lookup(tokens, model)
        if hit is None:
            return
        key = hit["key"]
        with self._lock:
            if (replica.tag, key) in self._adopted:
                return           # already holds it (published or adopted)
        if hit["holder"] == replica.tag:
            return
        holder = self._find_replica(hit["holder"])
        if holder is None and not hit.get("remote"):
            # OUR holder left the membership between publish and now:
            # entry is dead weight, drop it (locally and at the head)
            self.invalidate_holder(hit["holder"])
            return
        n = int(hit["n_tokens"])
        aid = next(self._adopt_seq)
        fleet = self.fleet
        fleet.note("adopt_begin", replica=replica.tag,
                   holder=hit["holder"], adopt=aid, tokens=n)
        try:
            self._chaos("prefix_fetch", replica=replica.tag,
                        holder=hit["holder"], holder_replica=holder,
                        key=key, tokens=n)
            if holder is not None:
                payload = self._body_call(
                    holder, "prefix_extract",
                    (model, tokens[:n], int(hit["generation"])))
            else:
                # head-directory hit from a sibling fleet process:
                # fetch over the node object/transfer plane instead
                payload = self._conduit_fetch(hit, tokens[:n])
            self._chaos("prefix_install", replica=replica.tag,
                        holder=hit["holder"], key=key, tokens=n)
            self._body_call(
                replica, "prefix_install",
                (model, tokens[:n], payload))
        except StalePrefixGeneration:
            # donated-pool recovery on the holder: purge everything
            # that generation advertised, then recompute locally.
            # Generation truth is global — mirror the drop to the head
            # so sibling fleets stop chasing the same dead entries
            self.directory.invalidate_stale(hit["holder"],
                                            hit["generation"])
            self._head_send({"t": "prefix_invalidate",
                             "holder": hit["holder"],
                             "stale_generation": hit["generation"]})
            self._count("remote_fetch_failures")
            self._count("fallback_recomputes")
            fleet.note("adopt_fallback", replica=replica.tag,
                       holder=hit["holder"], adopt=aid,
                       reason="stale_generation")
        except Exception as e:
            # holder died mid-fetch, drain raced in, transfer timeout,
            # receiver block pressure, eviction raced the fetch — all
            # one outcome: silent downgrade to local recompute
            if isinstance(e, PrefixUnavailable):
                self.directory.purge(key)
                self._head_send({"t": "prefix_invalidate", "key": key})
            self._count("remote_fetch_failures")
            self._count("fallback_recomputes")
            fleet.note("adopt_fallback", replica=replica.tag,
                       holder=hit["holder"], adopt=aid,
                       reason=type(e).__name__)
        else:
            self._count("remote_hits")
            with self._lock:
                self._adopted.add((replica.tag, key))
            fleet.note("adopt_complete", replica=replica.tag,
                       holder=hit["holder"], adopt=aid, tokens=n)

    # ------------------------------------------------------------ plumbing

    def _head_client(self):
        """The connected runtime's node client (the message then
        proxies head-ward via the node's cluster-scope routing), or
        None for pure in-proc serving with no ``ray_tpu.init()`` —
        there the local directory IS the whole plane."""
        try:
            import ray_tpu
            if not ray_tpu.is_initialized():
                return None
            return ray_tpu.get_runtime().client
        except Exception:
            return None

    def _head_send(self, msg: dict) -> None:
        """Best-effort head-directory mirror: a lost mirror costs a
        sibling fleet one recomputed prefill (or one doomed fetch that
        falls back), never correctness — so failures are swallowed and
        the bound on the request path is one short round-trip."""
        client = self._head_client()
        if client is None:
            return
        try:
            client.request(msg, timeout=min(2.0, self.fetch_timeout_s))
        except Exception:
            pass

    def _head_lookup(self, keys: list) -> Optional[dict]:
        client = self._head_client()
        if client is None:
            return None
        try:
            reply = client.request(
                {"t": "prefix_lookup", "keys": keys},
                timeout=min(2.0, self.fetch_timeout_s))
        except Exception:
            return None
        hit = reply.get("hit")
        return dict(hit) if isinstance(hit, dict) else None

    def _conduit_fetch(self, hit: dict, tokens: list) -> dict:
        """Fetch a foreign holder's K/V bytes over the node
        object/transfer plane (core/node_transfer.py
        ``_h_block_fetch``), addressed by the engine-registry name the
        holder published.  Typed prefix errors are reconstructed from
        the reply's error name so the caller's fallback ladder (stale
        → invalidate generation, unavailable → purge key) behaves
        exactly as for an in-fleet fetch."""
        client = self._head_client()
        if client is None or not hit.get("engine"):
            raise PrefixUnavailable(
                f"no conduit to foreign holder {hit['holder']!r}")
        reply = client.request(
            {"t": "block_fetch", "engine": hit["engine"],
             "tokens": list(tokens),
             "generation": int(hit["generation"])},
            timeout=self.fetch_timeout_s)
        err = reply.get("error")
        if err:
            if reply.get("error_type") == "StalePrefixGeneration":
                raise StalePrefixGeneration(err)
            raise PrefixUnavailable(err)
        import numpy as np
        try:
            dt = np.dtype(reply["dtype"])
        except TypeError:
            import ml_dtypes   # bfloat16 et al (registered by jax)
            dt = np.dtype(getattr(ml_dtypes, reply["dtype"]))
        shape = tuple(reply["shape"])
        return {
            "k": np.frombuffer(reply["k"], dtype=dt).reshape(shape),
            "v": np.frombuffer(reply["v"], dtype=dt).reshape(shape),
            "generation": int(reply["generation"]),
            "n_tokens": int(reply["n_tokens"]),
            "block_size": int(reply["block_size"]),
        }

    def _find_replica(self, tag: str):
        state = self.fleet.state
        with state._lock:
            for r in state.replicas:
                if r.tag == tag and r.lifecycle == "active":
                    return r
        return None

    def _body_call(self, replica, method: str, args: tuple):
        """Replica-body method call.  In-process bodies are direct
        calls; actor replicas go through the core runtime — the K/V
        payload then rides the existing object/transfer plane
        (core/node_transfer.py), the same conduit owner_handoff uses.
        Typed prefix errors survive the hop either way."""
        if replica.is_actor:
            import ray_tpu
            ref = replica.impl.handle_request.remote(method, args, {})
            return ray_tpu.get(ref, timeout=self.fetch_timeout_s)
        return replica.impl.handle_request(method, args, {})
