"""ray_tpu.serve: model serving (reference capability: python/ray/serve —
SURVEY.md §2.4; §7 M8 controller/proxy/replica triangle)."""

from __future__ import annotations

from typing import Optional, Union

from ray_tpu.serve.batching import batch
from ray_tpu.serve.controller import ServeController
from ray_tpu.serve.deployment import (AutoscalingConfig, Deployment,
                                      DeploymentOptions, deployment)
from ray_tpu.serve.handle import DeploymentHandle, ServeResponse
from ray_tpu.serve.http_proxy import HttpProxy

_controller: Optional[ServeController] = None
_proxy: Optional[HttpProxy] = None


def _get_controller() -> ServeController:
    global _controller
    if _controller is None:
        _controller = ServeController()
    return _controller


def run(dep: Deployment, *, use_actors: Optional[bool] = None,
        http: bool = False, port: int = 0) -> DeploymentHandle:
    """Deploy and return a handle (reference: serve.run api.py:455)."""
    global _proxy
    ctrl = _get_controller()
    state = ctrl.deploy(dep, use_actors=use_actors)
    if http and _proxy is None:
        _proxy = HttpProxy(ctrl, port=port)
        _proxy.start()
    return DeploymentHandle(state)


def get_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(_get_controller().get(name))


def delete(name: str) -> None:
    _get_controller().delete(name)


def proxy_address() -> Optional[str]:
    return f"http://{_proxy.host}:{_proxy.port}" if _proxy else None


def status() -> dict:
    ctrl = _get_controller()
    return {name: {"replicas": len(st.replicas),
                   "ongoing_per_replica": st.ongoing_per_replica()}
            for name, st in ctrl.deployments.items()}


def shutdown() -> None:
    global _controller, _proxy
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    if _controller is not None:
        _controller.shutdown()
        _controller = None


__all__ = [
    "deployment", "Deployment", "DeploymentOptions", "AutoscalingConfig",
    "DeploymentHandle", "ServeResponse", "ServeController", "HttpProxy",
    "batch", "run", "get_handle", "delete", "shutdown", "status",
    "proxy_address",
]
