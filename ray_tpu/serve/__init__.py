"""ray_tpu.serve: model serving (reference capability: python/ray/serve —
SURVEY.md §2.4; §7 M8 controller/proxy/replica triangle)."""

from __future__ import annotations

from typing import Optional, Union

from ray_tpu.serve.asgi import ingress
from ray_tpu.serve.batching import batch
from ray_tpu.serve.controller import (ReplicaContext, ServeController,
                                      get_replica_context)
from ray_tpu.serve.deployment import (AutoscalingConfig, Deployment,
                                      DeploymentOptions, deployment)
from ray_tpu.serve.handle import (DeploymentHandle, RemoteDeploymentHandle,
                                  ServeResponse)
from ray_tpu.serve.http_proxy import HttpProxy

_controller: Optional[ServeController] = None
_proxy: Optional[HttpProxy] = None


def _get_controller() -> ServeController:
    global _controller
    if _controller is None:
        _controller = ServeController()
    return _controller


def _deploy_tree(dep: Deployment, ctrl: ServeController,
                 use_actors: Optional[bool],
                 seen: dict) -> DeploymentHandle:
    """Deployment graph: Deployment-valued init args are child nodes —
    deploy children first, inject handles in their place (reference:
    serve deployment graphs on the DAG layer, serve/dag/; handles cross
    process boundaries as RemoteDeploymentHandle via pickling)."""
    if dep.name in seen:
        return seen[dep.name]

    def resolve(v):
        return (_deploy_tree(v, ctrl, use_actors, seen)
                if isinstance(v, Deployment) else v)

    resolved = dep.bind(*(resolve(a) for a in dep.init_args),
                        **{k: resolve(v)
                           for k, v in dep.init_kwargs.items()})
    state = ctrl.deploy(resolved, use_actors=use_actors)
    handle = DeploymentHandle(state)
    seen[dep.name] = handle
    return handle


def run(dep: Deployment, *, use_actors: Optional[bool] = None,
        http: bool = False, port: int = 0,
        proxy: str = "asyncio") -> DeploymentHandle:
    """Deploy (a graph of) deployment(s) and return the root handle
    (reference: serve.run api.py:455).  proxy: "asyncio" (concurrent,
    streaming + ASGI capable) or "threaded" (the round-1 stdlib
    server)."""
    global _proxy
    ctrl = _get_controller()
    handle = _deploy_tree(dep, ctrl, use_actors, {})
    if http and _proxy is None:
        if proxy == "asyncio":
            from ray_tpu.serve.asgi import AsyncHttpProxy
            _proxy = AsyncHttpProxy(ctrl, port=port)
        else:
            _proxy = HttpProxy(ctrl, port=port)
        _proxy.start()
    return handle


def get_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(_get_controller().get(name))


def delete(name: str) -> None:
    _get_controller().delete(name)


def proxy_address() -> Optional[str]:
    return f"http://{_proxy.host}:{_proxy.port}" if _proxy else None


def status() -> dict:
    ctrl = _get_controller()
    return {name: {"replicas": len(st.replicas),
                   "ongoing_per_replica": st.ongoing_per_replica(),
                   **st.request_metrics}
            for name, st in ctrl.deployments.items()}


def metrics_snapshot() -> list:
    """Per-deployment request metrics in the exporter's tuple format
    (reference: serve's Prometheus metrics via the metrics agent)."""
    ctrl = _get_controller()
    reqs, errs, lat = {}, {}, {}
    for name, st in ctrl.deployments.items():
        key = (("deployment", name),)
        m = st.request_metrics
        reqs[key] = m["requests"]
        errs[key] = m["errors"]
        lat[key] = m["latency_sum_s"]
    out = [
        ("serve_requests_total", "counter",
         "Requests completed per deployment", reqs),
        ("serve_request_errors_total", "counter",
         "Requests errored per deployment", errs),
        ("serve_request_latency_seconds_sum", "counter",
         "Summed request latency per deployment", lat),
    ]
    # inference-engine gauges ride the same endpoint when any engine is
    # live in this process (lazy: never pulls jax in for non-LLM serving)
    import sys
    inference = sys.modules.get("ray_tpu.inference")
    if inference is not None:
        try:
            out += inference.metrics_snapshot()
        except Exception:
            pass
    # fleet ingress counters, one series per fleet-enabled deployment
    # (same laziness: only when the fleet layer has been imported)
    fleet_mod = sys.modules.get("ray_tpu.serve.fleet")
    if fleet_mod is not None:
        try:
            out += fleet_mod.metrics_snapshot()
        except Exception:
            pass
    return out


def start_metrics_exporter(port: int = 0):
    """Expose serve metrics at /metrics (reference: per-node metrics
    agent endpoint)."""
    from ray_tpu.metrics import MetricsExporter
    exporter = MetricsExporter(metrics_snapshot, port=port)
    return exporter


def shutdown() -> None:
    global _controller, _proxy
    import sys
    # join the fleet ingress worker threads FIRST: a parked worker still
    # holds its last request's replica/engine frame, and tearing the
    # controller down under it turns that into a GC-window race (lazy:
    # only when the fleet layer was ever imported)
    fleet_mod = sys.modules.get("ray_tpu.serve.fleet")
    if fleet_mod is not None:
        try:
            fleet_mod.join_worker_threads()
        except Exception:
            pass
    if _proxy is not None:
        _proxy.stop()
        _proxy = None
    if _controller is not None:
        _controller.shutdown()
        _controller = None


__all__ = [
    "deployment", "Deployment", "DeploymentOptions", "AutoscalingConfig",
    "DeploymentHandle", "RemoteDeploymentHandle", "ServeResponse",
    "ServeController", "ReplicaContext", "get_replica_context",
    "HttpProxy", "ingress", "batch", "run",
    "get_handle", "delete", "shutdown", "status", "proxy_address",
]

from ray_tpu import usage_stats as _usage_stats
_usage_stats.record_library_usage("serve")
