"""Deployments: the serveable unit.

Reference capability: @serve.deployment (python/ray/serve/deployment.py)
with num_replicas / max_concurrent_queries / autoscaling options, and
the user class contract (__call__ or named methods; async optional).
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Union


@dataclass
class AutoscalingConfig:
    """(reference: serve autoscaling_policy.py calculate_desired_num_replicas
    — scale to keep per-replica ongoing requests near the target)"""
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0


@dataclass
class DeploymentOptions:
    name: str = ""
    num_replicas: int = 1
    max_concurrent_queries: int = 8
    autoscaling: Optional[AutoscalingConfig] = None
    ray_actor_options: dict = field(default_factory=dict)
    use_actors: Optional[bool] = None    # None = actors iff runtime up


class Deployment:
    """A configured (not yet running) deployment; ``serve.run`` turns it
    into replicas (reference: Deployment.bind/deploy split)."""

    def __init__(self, cls_or_fn: Union[type, Callable],
                 options: DeploymentOptions,
                 init_args: tuple = (), init_kwargs: Optional[dict] = None):
        self._target = cls_or_fn
        self.options = options
        self.init_args = init_args
        self.init_kwargs = init_kwargs or {}

    @property
    def name(self) -> str:
        return self.options.name or getattr(
            self._target, "__name__", "deployment")

    def bind(self, *args, **kwargs) -> "Deployment":
        d = copy.copy(self)
        d.init_args = args
        d.init_kwargs = kwargs
        return d

    def set_options(self, **kw) -> "Deployment":
        d = copy.copy(self)
        d.options = copy.copy(self.options)
        for k, v in kw.items():
            setattr(d.options, k, v)
        return d

    def build_replica(self):
        """Instantiate the user target (one replica's worth)."""
        t = self._target
        if isinstance(t, type):
            return t(*self.init_args, **self.init_kwargs)
        # bare function deployment: wrap as single-method callable
        fn = t

        class _FnReplica:
            def __call__(self, *a, **kw):
                return fn(*a, **kw)

        return _FnReplica()


def deployment(cls_or_fn=None, *, name: Optional[str] = None,
               num_replicas: int = 1, max_concurrent_queries: int = 8,
               autoscaling_config: Optional[dict] = None,
               ray_actor_options: Optional[dict] = None):
    """@serve.deployment decorator (reference: serve/api.py deployment)."""

    def wrap(target):
        auto = (AutoscalingConfig(**autoscaling_config)
                if isinstance(autoscaling_config, dict)
                else autoscaling_config)
        return Deployment(target, DeploymentOptions(
            name=name or getattr(target, "__name__", "deployment"),
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            autoscaling=auto,
            ray_actor_options=ray_actor_options or {}))

    return wrap(cls_or_fn) if cls_or_fn is not None else wrap
