"""Long-poll config propagation.

Reference capability: serve's LongPollHost/LongPollClient
(python/ray/serve/_private/long_poll.py:185 — listeners block on a set
of keys until any snapshot's version advances, then receive the changed
snapshots).  The host lives in the controller; in-process listeners
(the HTTP proxy's route table) block on a Condition, and snapshots are
mirrored into the core KV store so cross-process handles can refresh
replica membership without a controller hop.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional


class LongPollHost:
    def __init__(self):
        self._lock = threading.Condition()
        self._versions: dict[str, int] = {}
        self._snapshots: dict[str, Any] = {}

    def notify(self, key: str, snapshot: Any) -> None:
        with self._lock:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._snapshots[key] = snapshot
            self._lock.notify_all()

    def drop(self, key: str) -> None:
        with self._lock:
            self._versions[key] = self._versions.get(key, 0) + 1
            self._snapshots.pop(key, None)
            self._lock.notify_all()

    def get(self, key: str):
        with self._lock:
            return self._versions.get(key, 0), self._snapshots.get(key)

    def listen(self, known: dict[str, int],
               timeout: Optional[float] = 30.0) -> dict[str, tuple]:
        """Block until any key in `known` has a version newer than the
        caller's, then return {key: (version, snapshot)} for the changed
        keys.  Empty dict on timeout (the client just re-polls —
        long-poll semantics, reference long_poll.py listen_for_change)."""
        with self._lock:
            def changed():
                return {k: (self._versions.get(k, 0), self._snapshots.get(k))
                        for k, v in known.items()
                        if self._versions.get(k, 0) > v}
            out = changed()
            if out:
                return out
            self._lock.wait(timeout)
            return changed()


class LongPollClient:
    """Background listener: invokes ``callback(key, snapshot)`` whenever
    a watched key changes (reference: LongPollClient callbacks)."""

    def __init__(self, host: LongPollHost, keys: list[str],
                 callback: Callable[[str, Any], None]):
        self._host = host
        self._keys = {k: 0 for k in keys}
        self._callback = callback
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="raytpu-serve-longpoll")
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            updates = self._host.listen(dict(self._keys), timeout=1.0)
            for key, (version, snapshot) in updates.items():
                self._keys[key] = version
                try:
                    self._callback(key, snapshot)
                except Exception:
                    import traceback
                    traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
