"""@serve.batch: dynamic request batching.

Reference capability: python/ray/serve/batching.py @serve.batch — queue
individual calls, flush when max_batch_size is reached or
batch_wait_timeout_s elapses, fan results back out.  The decorated
method receives a LIST of requests and must return a list of equal
length.  This is the serving-side MXU lever: one batched forward instead
of N singles.

Each replica INSTANCE gets its own batcher (descriptor protocol) — a
shared class-level queue would route one replica's requests into
another's state and break the router's per-replica accounting.
"""

from __future__ import annotations

import functools
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Optional


class _Batcher:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout = batch_wait_timeout_s
        self._lock = threading.Lock()
        self._queue: list[tuple[Any, Future]] = []
        self._timer: Optional[threading.Timer] = None

    def submit(self, instance, item) -> Future:
        fut: Future = Future()
        flush_now = False
        with self._lock:
            self._queue.append((item, fut))
            if len(self._queue) >= self.max_batch_size:
                flush_now = True
            elif self._timer is None:
                self._timer = threading.Timer(
                    self.timeout, self._flush, (instance,))
                self._timer.daemon = True
                self._timer.start()
        if flush_now:
            self._flush(instance)
        return fut

    def _flush(self, instance):
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._queue = self._queue, []
        if not batch:
            return
        items = [b[0] for b in batch]
        try:
            results = (self.fn(instance, items) if instance is not None
                       else self.fn(items))
            # Strict fan-out contract: a dict / str / generator of the
            # right *length* would zip apart into keys / characters /
            # nothing and hand each caller silently-wrong results, so
            # only an explicit sequence with one entry per request is
            # accepted.  numpy arrays fan out along their first axis.
            name = getattr(self.fn, "__qualname__", repr(self.fn))
            if isinstance(results, (str, bytes, dict)) or not hasattr(
                    results, "__len__"):
                raise TypeError(
                    f"@serve.batch function {name!r} must return a "
                    f"list/tuple of {len(items)} results (one per "
                    f"batched request), got {type(results).__name__}")
            if len(results) != len(items):
                raise ValueError(
                    f"@serve.batch function {name!r} returned "
                    f"{len(results)} results for a batch of "
                    f"{len(items)} requests; each request must map to "
                    "exactly one result, in order")
            for (_, fut), r in zip(batch, results):
                fut.set_result(r)
        except BaseException as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


class _BatchDescriptor:
    """Binds a per-instance batcher on attribute access; calling the
    descriptor object directly covers free-function deployments."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait = batch_wait_timeout_s
        self._attr = f"__batcher_{fn.__name__}"
        self._free_batcher: Optional[_Batcher] = None
        self._free_lock = threading.Lock()
        functools.update_wrapper(self, fn)

    def __set_name__(self, owner, name):
        self._attr = f"__batcher_{name}"

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        batcher = obj.__dict__.get(self._attr)
        if batcher is None:
            batcher = obj.__dict__.setdefault(
                self._attr, _Batcher(self._fn, self._max, self._wait))

        def bound(item):
            return batcher.submit(obj, item).result()

        functools.update_wrapper(bound, self._fn)
        bound._batcher = batcher
        return bound

    def __call__(self, item):
        # free-function form: one module-level batcher, fn(items)
        with self._free_lock:
            if self._free_batcher is None:
                self._free_batcher = _Batcher(self._fn, self._max, self._wait)
        return self._free_batcher.submit(None, item).result()


def batch(_fn=None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: calls collect into lists (reference: serve/batching.py).

    The wrapped call BLOCKS until its result is ready, so replica
    concurrency (threads / max_concurrent_queries) provides the overlap
    that fills batches.
    """

    def wrap(fn):
        return _BatchDescriptor(fn, max_batch_size, batch_wait_timeout_s)

    return wrap(_fn) if _fn is not None else wrap
