"""Request quality-of-service vocabulary shared by the serve fleet and
the inference engine.

Lives at the serve layer (jax-free) so the generic fleet machinery —
admission control, routing, multiplexing — never has to import the
inference stack (which pulls in jax) just for two priority ints and an
exception class; the engine imports FROM here and re-exports for
compatibility.
"""

from __future__ import annotations

# priority classes: lower admits first.  Interactive requests preempt
# batch ones wherever a queue is drained — the ingress admission queue
# and the engine's prefill-boundary admission both order by
# (priority, arrival).
PRIORITY_INTERACTIVE = 0
PRIORITY_BATCH = 1

_PRIORITY_NAMES = {"interactive": PRIORITY_INTERACTIVE,
                   "batch": PRIORITY_BATCH}


def parse_priority(value) -> int:
    """"interactive"/"batch"/int → priority class.  Unknown strings
    raise so a typo'd class is a clean client error, not a silently-
    batch request."""
    if value is None:
        return PRIORITY_BATCH
    if isinstance(value, str):
        try:
            return _PRIORITY_NAMES[value.lower()]
        except KeyError:
            raise ValueError(
                f"unknown priority {value!r} (expected one of "
                f"{sorted(_PRIORITY_NAMES)})") from None
    return int(value)


class ReplicaDeadError(RuntimeError):
    """The serving replica died with this request queued or in flight.
    The fleet layer treats it as retriable: the request had no
    observable side effects, so it re-routes to another replica
    (streams replay and skip the already-delivered prefix).  The
    engine's EngineStoppedError subclasses this."""


class EngineDrainingError(ReplicaDeadError):
    """The serving replica is DRAINING (planned scale-down): it finishes
    what it already holds but admits nothing new.  A typed subclass so
    the ingress maps it to a re-route — like a replica death, the
    request had no observable side effects — but ACCOUNTS it as
    ``resumed_scale_down``, never as a failure resume, and never as a
    500.  Lives here (jax-free) so the generic fleet machinery can
    classify without importing the inference stack."""


class PrefixTransferError(RuntimeError):
    """Base of the cluster-prefix-plane failure vocabulary.  EVERY
    subclass means the same thing to the fleet layer: the remote
    adoption is off, fall back to local chunked-prefill recompute — a
    prefix transfer failure is NEVER a request error (the robustness
    spine of the cluster prefix cache).  Typed so the plane can also
    tell *why* (purge a stale directory entry vs count a fetch
    failure); jax-free so the directory/head never import the
    inference stack."""


class StalePrefixGeneration(PrefixTransferError):
    """The holder's block pool was reset (donated-buffer recovery)
    since the directory entry was published: its generation counter
    moved on, so the advertised blocks no longer hold the advertised
    tokens.  The caller must purge the directory entry — a recovered
    pool's old block ids must never be served."""


class PrefixUnavailable(PrefixTransferError):
    """The holder no longer caches the requested prefix (LRU-evicted
    under pool pressure, or the engine has no radix index / geometry
    mismatch).  Benign: the adopter recomputes locally."""


class PrefixInstallPressure(PrefixTransferError):
    """The ADOPTER could not find blocks for the fetched prefix without
    preempting live requests — adoption is an optimization and never
    preempts real work for hoped-for reuse.  The fetched bytes are
    dropped and the request recomputes locally."""
