"""HTTP ingress: JSON-over-HTTP proxy in front of the controller.

Reference capability: HTTPProxyActor/uvicorn ingress
(python/ray/serve/_private/http_proxy.py:399,230 — route → deployment →
replica).  Stdlib ThreadingHTTPServer keeps it dependency-free; each
request thread blocks on the handle, so max_concurrent_queries
backpressure applies end to end.

Routes: POST/GET /<deployment> with a JSON body → the deployment's
__call__ gets the parsed JSON (or the raw body string if not JSON);
response is JSON-encoded.  GET /-/healthz and /-/routes are control
endpoints.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

import numpy as np


def _jsonable(x):
    if isinstance(x, np.ndarray):
        return x.tolist()
    if isinstance(x, np.generic):
        return x.item()
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    try:
        import jax
        if isinstance(x, jax.Array):
            return np.asarray(x).tolist()
    except Exception:
        pass
    return x


class HttpProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.serve.handle import DeploymentHandle
        self.controller = controller

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _reply(self, code: int, payload, extra_headers=()):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in extra_headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _route(self):
                path = self.path.strip("/").split("?")[0]
                if path == "-/healthz":
                    return self._reply(200, {"status": "ok"})
                if path == "-/routes":
                    return self._reply(
                        200, sorted(controller.deployments.keys()))
                name = path.split("/")[0]
                try:
                    state = controller.get(name)
                except KeyError:
                    return self._reply(404, {"error": f"no route /{name}"})
                n = int(self.headers.get("Content-Length") or 0)
                raw = self.rfile.read(n) if n else b""
                try:
                    arg = json.loads(raw) if raw else None
                except json.JSONDecodeError:
                    arg = raw.decode("utf-8", "replace")
                handle = DeploymentHandle(state)
                try:
                    out = handle.remote(arg).result(timeout=120)
                    self._reply(200, {"result": _jsonable(out)})
                except Exception as e:
                    from ray_tpu.serve.asgi import _shed_retry_after
                    ra = _shed_retry_after(e)
                    if ra is not None:   # fleet shed: 429, not a fault
                        import math
                        self._reply(429, {"error": str(e),
                                          "retry_after_s": ra},
                                    [("Retry-After",
                                      str(max(1, math.ceil(ra))))])
                    else:
                        self._reply(500, {"error": str(e)})

            do_GET = _route
            do_POST = _route

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
