"""Asyncio ingress: concurrent HTTP proxy with streaming + ASGI support.

Reference capability: the uvicorn/starlette proxy
(python/ray/serve/_private/http_proxy.py:230,399 — an asyncio event
loop multiplexes thousands of in-flight requests; responses may stream;
user apps may be ASGI applications via @serve.ingress).  Dependency-free
here: a hand-rolled HTTP/1.1 server on asyncio.start_server, chunked
transfer-encoding for iterator results, and a minimal ASGI 3.0 driver
for ingress apps.

Routes stay in a local table refreshed by the controller's long-poll
host — the proxy never reaches into controller state per request
(reference: proxy route table via LongPollClient).
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Optional
from urllib.parse import unquote, urlparse

from ray_tpu.serve.deployment import Deployment, DeploymentOptions
from ray_tpu.serve.http_proxy import _jsonable
from ray_tpu.serve.long_poll import LongPollClient


class _ASGIReplica:
    """Replica body driving a user ASGI app: one request-response cycle
    per call, messages collected and returned as a plain dict so the
    result crosses process boundaries."""

    def __init__(self, app):
        self._app = app

    def handle_asgi(self, scope: dict, body: bytes) -> dict:
        async def drive():
            sent_body = False
            messages: list = []

            async def receive():
                nonlocal sent_body
                if sent_body:
                    return {"type": "http.disconnect"}
                sent_body = True
                return {"type": "http.request", "body": body,
                        "more_body": False}

            async def send(msg):
                messages.append(msg)

            full_scope = dict(scope)
            full_scope.setdefault("type", "http")
            full_scope.setdefault("asgi", {"version": "3.0"})
            await self._app(full_scope, receive, send)
            return messages

        messages = asyncio.run(drive())
        status, headers, chunks = 200, [], []
        for m in messages:
            if m["type"] == "http.response.start":
                status = m["status"]
                headers = [(bytes(k).decode("latin1"),
                            bytes(v).decode("latin1"))
                           for k, v in m.get("headers", [])]
            elif m["type"] == "http.response.body":
                chunks.append(bytes(m.get("body", b"")))
        return {"status": status, "headers": headers,
                "body": b"".join(chunks)}


def ingress(asgi_app, *, name: Optional[str] = None,
            num_replicas: int = 1,
            max_concurrent_queries: int = 32) -> Deployment:
    """Wrap an ASGI application as a deployment (reference:
    @serve.ingress(fastapi_app), serve/api.py ingress)."""
    dep = Deployment(_ASGIReplica, DeploymentOptions(
        name=name or getattr(asgi_app, "__name__", "asgi_app"),
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries),
        init_args=(asgi_app,))
    dep.is_asgi = True
    return dep


def _shed_retry_after(e: BaseException):
    """Seconds from a fleet ShedError (duck-typed so this module never
    imports the fleet/inference stack), else None."""
    ra = getattr(e, "retry_after_s", None)
    try:
        return float(ra) if ra is not None else None
    except (TypeError, ValueError):
        return None


class AsyncHttpProxy:
    """Concurrent HTTP/1.1 ingress on an asyncio loop thread.

    Each connection is an asyncio task; replica calls run on the default
    executor so slow handlers never stall the accept loop.  Iterator /
    generator results stream as chunked transfer-encoding.  Fleet-shed
    requests (admission refusal) come back as ``429`` with a
    ``Retry-After`` header instead of a generic 500."""

    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        self.controller = controller
        self._host_arg, self._port_arg = host, port
        self.host: str = host
        self.port: int = 0
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._server = None
        self._started = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # dedicated, sized pool for blocking replica calls: the loop's
        # default executor is shared and small, which would head-of-line
        # block unrelated requests behind slow handlers.  Sized for
        # fleet-scale ingress: each in-flight request holds one worker
        # for its full latency, and admission (not this pool) must be
        # what says no — a too-small pool is an invisible unbounded
        # queue in FRONT of the admission controller
        from concurrent.futures import ThreadPoolExecutor
        self._executor = ThreadPoolExecutor(
            max_workers=256, thread_name_prefix="raytpu-serve-call")
        # long-polled route table: never touch controller state per
        # request (reference: proxy LongPollClient on route updates)
        self._routes: set[str] = set(controller.deployments.keys())
        self._lp = LongPollClient(
            controller.long_poll, ["routes"],
            lambda key, snapshot: self._set_routes(snapshot))

    def _set_routes(self, snapshot) -> None:
        self._routes = set(snapshot or ())

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raytpu-serve-asgi")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("asyncio proxy failed to start")

    def _run(self) -> None:
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)

        async def boot():
            self._server = await asyncio.start_server(
                self._handle_conn, self._host_arg, self._port_arg)
            self.port = self._server.sockets[0].getsockname()[1]
            self._started.set()
        self._loop.run_until_complete(boot())
        try:
            self._loop.run_forever()
        finally:
            self._loop.close()

    def stop(self) -> None:
        self._lp.stop()
        self._executor.shutdown(wait=False)
        if self._loop is None:
            return

        def _shutdown():
            if self._server is not None:
                self._server.close()
            self._loop.stop()
        self._loop.call_soon_threadsafe(_shutdown)
        if self._thread is not None:
            self._thread.join(timeout=5)

    # ------------------------------------------------------------- serving

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                try:
                    req = await self._read_request(reader)
                except ValueError:   # malformed framing (bad length)
                    await self._respond_json(writer, 400,
                                             {"error": "bad request"})
                    break
                if req is None:
                    break
                try:
                    keep_alive = await self._dispatch(writer, *req)
                except (ConnectionError, asyncio.IncompleteReadError):
                    raise
                except Exception as e:
                    # last-resort 500: a dispatch bug (or a replica
                    # iterator raising mid-stream) must never silently
                    # drop the connection; if headers already went out
                    # the write fails and the close signals truncation
                    try:
                        await self._respond_json(writer, 500,
                                                 {"error": str(e)})
                    except Exception:
                        pass
                    break
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader):
        line = await reader.readline()
        if not line:
            return None
        try:
            method, target, _version = line.decode("latin1").split()
        except ValueError:
            return None
        headers: dict[str, str] = {}
        while True:
            h = await reader.readline()
            if h in (b"\r\n", b"\n", b""):
                break
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        n = int(headers.get("content-length") or 0)
        body = await reader.readexactly(n) if n else b""
        return method, target, headers, body

    async def _dispatch(self, writer, method, target, headers,
                        body) -> bool:
        parsed = urlparse(target)
        path = unquote(parsed.path)
        stripped = path.strip("/")
        if stripped == "-/healthz":
            await self._respond_json(writer, 200, {"status": "ok"})
            return True
        if stripped == "-/routes":
            await self._respond_json(writer, 200, sorted(self._routes))
            return True
        name = stripped.split("/")[0]
        if name not in self._routes:
            await self._respond_json(writer, 404,
                                     {"error": f"no route /{name}"})
            return True
        try:
            state = self.controller.get(name)
        except KeyError:
            await self._respond_json(writer, 404,
                                     {"error": f"no route /{name}"})
            return True

        loop = asyncio.get_running_loop()
        if getattr(state.deployment, "is_asgi", False):
            scope = {
                "type": "http", "method": method, "path": path,
                "raw_path": path.encode(), "root_path": "",
                "query_string": parsed.query.encode(),
                "headers": [(k.encode("latin1"), v.encode("latin1"))
                            for k, v in headers.items()],
            }
            from ray_tpu.serve.handle import DeploymentHandle
            handle = DeploymentHandle(state, "handle_asgi")
            try:
                out = await loop.run_in_executor(
                    self._executor,
                    lambda: handle.remote(scope, body).result(timeout=120))
            except Exception as e:
                # same contract as the JSON path: app errors become 500s,
                # never dropped connections
                await self._respond_json(writer, 500, {"error": str(e)})
                return True
            await self._respond_raw(writer, out["status"], out["headers"],
                                    out["body"])
            return True

        try:
            arg = json.loads(body) if body else None
        except json.JSONDecodeError:
            arg = body.decode("utf-8", "replace")
        if isinstance(arg, dict) and getattr(state, "fleet", None) \
                is not None:
            # fleet envelope fields may ride headers (curl-friendly);
            # the JSON body wins when both are present
            for header, field in (("x-priority", "priority"),
                                  ("x-model", "model")):
                v = headers.get(header)
                if v is not None:
                    arg.setdefault(field, v)
        from ray_tpu.serve.handle import DeploymentHandle
        handle = DeploymentHandle(state)
        try:
            out = await loop.run_in_executor(
                self._executor,
                lambda: handle.remote(arg).result(timeout=120))
        except Exception as e:
            retry_after = _shed_retry_after(e)
            if retry_after is not None:
                # admission refusal: explicit load shedding, not a
                # server fault — tell the client when to come back
                import math
                await self._respond_json(
                    writer, 429, {"error": str(e),
                                  "retry_after_s": retry_after},
                    extra_headers=[("Retry-After",
                                    str(max(1, math.ceil(retry_after))))])
                return True
            await self._respond_json(writer, 500, {"error": str(e)})
            return True
        if hasattr(out, "__next__") or hasattr(out, "__anext__"):
            try:
                await self._respond_stream(writer, out, loop)
            except (ConnectionError, asyncio.IncompleteReadError):
                raise
            except Exception:
                # headers are already on the wire: injecting a 500 would
                # corrupt the chunked framing, so close WITHOUT the
                # terminating 0-chunk — truncation is the error signal
                pass
            finally:
                # ALWAYS close the result generator: an abandoned
                # consumer (client disconnect mid-stream) must propagate
                # GeneratorExit into the replica body so the engine
                # request is cancelled and its slot freed — GC timing is
                # not a cancellation policy.  Async generators expose
                # aclose(), not close().
                aclose = getattr(out, "aclose", None)
                close = getattr(out, "close", None)
                try:
                    if aclose is not None:
                        await aclose()
                    elif close is not None:
                        await loop.run_in_executor(self._executor, close)
                except Exception:
                    pass
            return False   # chunked stream ends the connection
        await self._respond_json(writer, 200, {"result": _jsonable(out)})
        return True

    # ------------------------------------------------------------ responses

    async def _respond_json(self, writer, status: int, payload,
                            extra_headers=()) -> None:
        body = json.dumps(payload).encode()
        await self._respond_raw(
            writer, status,
            [("Content-Type", "application/json"), *extra_headers], body)

    async def _respond_raw(self, writer, status: int, headers, body: bytes):
        lines = [f"HTTP/1.1 {status} X".encode()]
        seen = {k.lower() for k, _ in headers}
        hdrs = list(headers)
        if "content-length" not in seen:
            hdrs.append(("Content-Length", str(len(body))))
        for k, v in hdrs:
            lines.append(f"{k}: {v}".encode("latin1"))
        writer.write(b"\r\n".join(lines) + b"\r\n\r\n" + body)
        await writer.drain()

    async def _respond_stream(self, writer, it, loop) -> None:
        """Chunked transfer-encoding over a (sync) iterator result —
        each chunk flushes as the replica produces it (reference:
        StreamingResponse through the proxy)."""
        writer.write(b"HTTP/1.1 200 X\r\n"
                     b"Content-Type: application/octet-stream\r\n"
                     b"Transfer-Encoding: chunked\r\n"
                     b"Connection: close\r\n\r\n")
        await writer.drain()

        async def write_chunk(chunk):
            data = (chunk if isinstance(chunk, bytes)
                    else json.dumps(_jsonable(chunk)).encode())
            writer.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
            await writer.drain()

        if hasattr(it, "__anext__"):
            # async generator results drive directly on this loop
            async for chunk in it:
                await write_chunk(chunk)
        else:
            _SENTINEL = object()

            def next_chunk():
                try:
                    return next(it)
                except StopIteration:
                    return _SENTINEL

            while True:
                chunk = await loop.run_in_executor(self._executor, next_chunk)
                if chunk is _SENTINEL:
                    break
                await write_chunk(chunk)
        writer.write(b"0\r\n\r\n")
        await writer.drain()
