"""DeploymentHandle: the client side of a deployment.

Reference capability: serve handles (python/ray/serve/handle.py
RayServeHandle.remote → router → replica).  ``handle.remote(...)``
returns a future-like; ``.result()`` blocks.  Actor replicas return
ObjectRefs (query runs in the replica process); in-process replicas run
on a worker thread pool so concurrent queries still overlap.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

from ray_tpu.serve.controller import DeploymentState, ReplicaHandle


def _is_timeout(e: BaseException) -> bool:
    from concurrent.futures import TimeoutError as FutTimeout
    try:
        from ray_tpu.core.client import GetTimeoutError
    except ImportError:  # pragma: no cover
        GetTimeoutError = ()
    return isinstance(e, (FutTimeout, TimeoutError, GetTimeoutError))


class ServeResponse:
    """Future-like wrapper (reference: DeploymentResponse)."""

    def __init__(self, resolve, cancel_release):
        self._resolve = resolve
        self._release = cancel_release
        self._done = False
        self._value = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done:
            try:
                self._value = self._resolve(timeout)
            except BaseException as e:
                if _is_timeout(e):
                    # request is still executing on the replica — keep
                    # its concurrency slot held and let the caller retry
                    raise
                self._error = e
            self._release()
            self._done = True
        if self._error is not None:
            raise self._error
        return self._value


class DeploymentHandle:
    _pool: Optional[ThreadPoolExecutor] = None
    _pool_lock = threading.Lock()

    def __init__(self, state: DeploymentState, method: str = "__call__"):
        self._state = state
        self._method = method

    @property
    def deployment_name(self) -> str:
        return self._state.deployment.name

    def options(self, *, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._state, method_name)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._state, name)

    @classmethod
    def _ensure_pool(cls) -> ThreadPoolExecutor:
        with cls._pool_lock:
            if cls._pool is None:
                cls._pool = ThreadPoolExecutor(max_workers=32)
        return cls._pool

    def remote(self, *args, **kwargs) -> ServeResponse:
        state, method = self._state, self._method
        replica = state.assign_replica()
        if replica.is_actor:
            ref = replica.impl.handle_request.remote(method, args, kwargs)

            def resolve(timeout):
                import ray_tpu
                # timeout=None means block until done (matches the
                # in-process Future path) — do not invent a deadline
                return ray_tpu.get(ref, timeout=timeout)
        else:
            fut: Future = self._ensure_pool().submit(
                replica.impl.handle_request, method, args, kwargs)

            def resolve(timeout):
                return fut.result(timeout)

        return ServeResponse(resolve, lambda: state.release(replica))
