"""DeploymentHandle: the client side of a deployment.

Reference capability: serve handles (python/ray/serve/handle.py
RayServeHandle.remote → router → replica).  ``handle.remote(...)``
returns a future-like; ``.result()`` blocks.  Actor replicas return
ObjectRefs (query runs in the replica process); in-process replicas run
on a worker thread pool so concurrent queries still overlap.
"""

from __future__ import annotations

import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Optional

from ray_tpu.serve.controller import DeploymentState, ReplicaHandle


def _is_timeout(e: BaseException) -> bool:
    from concurrent.futures import TimeoutError as FutTimeout
    try:
        from ray_tpu.core.client import GetTimeoutError
    except ImportError:  # pragma: no cover
        GetTimeoutError = ()
    return isinstance(e, (FutTimeout, TimeoutError, GetTimeoutError))


class ServeResponse:
    """Future-like wrapper (reference: DeploymentResponse)."""

    def __init__(self, resolve, cancel_release):
        self._resolve = resolve
        self._release = cancel_release
        self._done = False
        self._value = None
        self._error: Optional[BaseException] = None

    def result(self, timeout: Optional[float] = None):
        if not self._done:
            try:
                self._value = self._resolve(timeout)
            except BaseException as e:
                if _is_timeout(e):
                    # request is still executing on the replica — keep
                    # its concurrency slot held and let the caller retry
                    raise
                self._error = e
            self._release()
            self._done = True
        if self._error is not None:
            raise self._error
        return self._value


class DeploymentHandle:
    _pool: Optional[ThreadPoolExecutor] = None
    _pool_lock = threading.Lock()

    def __init__(self, state: DeploymentState, method: str = "__call__"):
        self._state = state
        self._method = method

    @property
    def deployment_name(self) -> str:
        return self._state.deployment.name

    def options(self, *, method_name: str) -> "DeploymentHandle":
        return DeploymentHandle(self._state, method_name)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self._state, name)

    @classmethod
    def _ensure_pool(cls) -> ThreadPoolExecutor:
        with cls._pool_lock:
            if cls._pool is None:
                cls._pool = ThreadPoolExecutor(max_workers=32)
        return cls._pool

    def _current_state(self) -> DeploymentState:
        """Re-resolve by name: a redeploy replaces the DeploymentState,
        and a handle bound to the dead one would spin on zero replicas
        forever."""
        try:
            from ray_tpu import serve as _serve
            ctrl = _serve._controller
            if ctrl is not None:
                st = ctrl.deployments.get(self._state.deployment.name)
                if st is not None and st is not self._state:
                    self._state = st
        except Exception:
            pass
        return self._state

    def remote(self, *args, **kwargs):
        import time as _time
        state, method = self._current_state(), self._method
        fleet = getattr(state, "fleet", None)
        if fleet is not None and method == "__call__":
            # fleet-enabled deployment: admission (may raise ShedError
            # — backpressure is synchronous by design) + occupancy
            # routing + resume-on-replica-death, instead of the
            # round-robin assign below
            return fleet.remote(args, kwargs)
        replica = state.assign_replica()
        t0 = _time.perf_counter()
        if replica.is_actor:
            ref = replica.impl.handle_request.remote(method, args, kwargs)

            def resolve_inner(timeout):
                import ray_tpu
                # timeout=None means block until done (matches the
                # in-process Future path) — do not invent a deadline
                return ray_tpu.get(ref, timeout=timeout)
        else:
            fut: Future = self._ensure_pool().submit(
                replica.impl.handle_request, method, args, kwargs)

            def resolve_inner(timeout):
                return fut.result(timeout)

        def resolve(timeout):
            try:
                out = resolve_inner(timeout)
            except BaseException as e:
                if not _is_timeout(e):   # timeouts retry; don't count
                    state.record_request(_time.perf_counter() - t0, True)
                raise
            state.record_request(_time.perf_counter() - t0, False)
            return out

        return ServeResponse(resolve, lambda: state.release(replica))

    def __reduce__(self):
        # a handle crossing a process boundary (deployment-graph child
        # injected into a replica's constructor) becomes a
        # RemoteDeploymentHandle that routes via the KV-mirrored replica
        # membership — the controller object cannot travel
        return (RemoteDeploymentHandle,
                (self.deployment_name, self._method))


class RemoteDeploymentHandle:
    """Process-portable deployment handle (the router half the reference
    ships inside every replica: _private/router.py + long-poll replica
    membership).  Replica actor handles come from the KV mirror the
    controller maintains; the snapshot refreshes on a short TTL or on
    call failure, so scaling/restarts propagate without a central hop
    per request."""

    REFRESH_S = 1.0

    def __init__(self, name: str, method: str = "__call__"):
        self._name = name
        self._method = method
        self._replicas: list = []
        self._maxq = 8
        self._fetched_at = 0.0
        self._rr = 0
        self._ongoing: dict[int, int] = {}   # replica index -> in-flight
        self._lock = threading.Lock()

    def options(self, *, method_name: str) -> "RemoteDeploymentHandle":
        return RemoteDeploymentHandle(self._name, method_name)

    def __getattr__(self, name: str) -> "RemoteDeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return RemoteDeploymentHandle(self._name, name)

    def __reduce__(self):
        return (RemoteDeploymentHandle, (self._name, self._method))

    def _refresh(self, force: bool = False) -> None:
        import time as _time
        now = _time.monotonic()
        with self._lock:
            if (not force and self._replicas
                    and now - self._fetched_at < self.REFRESH_S):
                return
        import cloudpickle
        import ray_tpu
        raw = ray_tpu.get_runtime().client.kv_get(
            f"serve:replicas:{self._name}".encode())
        if raw is None:
            raise RuntimeError(
                f"no replica membership for deployment {self._name!r} "
                "(not deployed with actor replicas?)")
        snap = cloudpickle.loads(raw)
        with self._lock:
            self._replicas = snap["replicas"]
            self._maxq = snap["max_concurrent_queries"]
            self._fetched_at = now
            # counts are keyed by actor id, so a refresh with unchanged
            # membership preserves in-flight bookkeeping and a reorder
            # can't misattribute load; drop counts for departed replicas
            live = {self._replica_key(r) for r in self._replicas}
            self._ongoing = {k: v for k, v in self._ongoing.items()
                             if k in live}

    @staticmethod
    def _replica_key(replica) -> str:
        try:
            return replica._actor_id.hex()
        except AttributeError:
            return str(id(replica))

    def _assign(self, timeout: float = 60.0):
        """Round-robin with per-handle max_concurrent_queries
        backpressure — the remote path must honor the same concurrency
        bound the local router enforces (router.py:221)."""
        import time as _time
        deadline = _time.monotonic() + timeout
        while True:
            self._refresh()
            with self._lock:
                n = len(self._replicas)
                if n == 0:
                    raise RuntimeError(f"deployment {self._name!r} has "
                                       "no actor replicas")
                for _ in range(n):
                    self._rr += 1
                    r = self._replicas[self._rr % n]
                    key = self._replica_key(r)
                    if self._ongoing.get(key, 0) < self._maxq:
                        self._ongoing[key] = self._ongoing.get(key, 0) + 1
                        return key, r
            if _time.monotonic() > deadline:
                raise RuntimeError(
                    f"deployment {self._name!r}: all replicas saturated "
                    f"for {timeout}s")
            _time.sleep(0.001)

    def _release(self, key: str) -> None:
        with self._lock:
            if self._ongoing.get(key, 0) > 0:
                self._ongoing[key] -= 1

    def remote(self, *args, **kwargs) -> ServeResponse:
        key, replica = self._assign()
        ref = replica.handle_request.remote(self._method, args, kwargs)

        def resolve(timeout):
            import ray_tpu
            try:
                return ray_tpu.get(ref, timeout=timeout)
            except Exception:
                # stale membership (replica died): refresh for the next
                # call, but never let the refresh mask the real failure
                try:
                    self._refresh(force=True)
                except Exception:
                    pass
                raise
        return ServeResponse(resolve, lambda: self._release(key))
