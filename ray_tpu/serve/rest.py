"""Serve REST config API + declarative config deploys.

Reference capability: the Serve REST surface served by the dashboard
(python/ray/serve/schema.py ServeDeploySchema /
ServeApplicationSchema; dashboard/modules/serve/serve_rest_api.py):
``PUT /api/serve/applications/`` deploys a declarative config of
applications (import_path + deployment overrides), ``GET`` returns
cluster serve status, ``DELETE`` tears everything down. The same
config shape drives ``serve deploy <config>`` / ``serve run`` in the
CLI.

Config shape (the subset of the reference schema implemented here):

    {"applications": [
        {"name": "app1",
         "import_path": "my.module:entrypoint",   # a Deployment (bound)
         "args": {...},                            # optional bind kwargs
         "deployments": [                          # per-deployment overrides
            {"name": "Model", "num_replicas": 2,
             "max_concurrent_queries": 8}]}]}
"""

from __future__ import annotations

import importlib
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

_applications: Dict[str, dict] = {}   # name -> {"import_path", "deployments"}
_lock = threading.Lock()              # guards _applications
_apply_lock = threading.Lock()        # serializes declarative PUT/DELETE


def _import_target(import_path: str):
    """'pkg.module:attr' → the attribute (a Deployment or bound graph)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path must look like 'module:attr', got "
            f"{import_path!r}")
    mod_name, _, attr = import_path.partition(":")
    mod = importlib.import_module(mod_name)
    target = mod
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def apply_config(config: dict, *, use_actors: Optional[bool] = None,
                 http: bool = False, port: int = 0) -> List[str]:
    """Deploy a declarative config (reference: ServeDeploySchema apply —
    serve_rest_api.py put handler). Returns deployed app names.

    Fully declarative like the reference: apps previously deployed via
    this API but absent from the new config are torn down. Concurrent
    PUT/DELETEs are serialized (the reference controller applies configs
    from a single control loop)."""
    from ray_tpu import serve
    from ray_tpu.serve.deployment import Deployment

    with _apply_lock:
        apps = config.get("applications", [])

        # pass 1 — resolve and validate EVERYTHING before touching any
        # running state, so a bad config rejects without side effects
        def tree_names(dep) -> set:
            names = {dep.name}
            for v in (*dep.init_args, *dep.init_kwargs.values()):
                if isinstance(v, Deployment):
                    names |= tree_names(v)
            return names

        plans = []   # (name, app-dict, target, overrides, deployment set)
        for app in apps:
            name = app.get("name") or app["import_path"]
            target = _import_target(app["import_path"])
            if callable(target) and not isinstance(target, Deployment):
                target = target(**app.get("args", {}))
            if not isinstance(target, Deployment):
                raise TypeError(
                    f"{app['import_path']} resolved to "
                    f"{type(target).__name__}, expected a Deployment")
            overrides = {d["name"]: {k: v for k, v in d.items()
                                     if k != "name"}
                         for d in app.get("deployments", [])}
            # validate every override key here so a typo can't reject
            # the config AFTER pass 2 has torn running apps down
            from ray_tpu.serve.deployment import DeploymentOptions
            for dep_name, opts in overrides.items():
                for key in opts:
                    if key != "num_replicas" \
                            and not hasattr(DeploymentOptions, key) \
                            and key not in DeploymentOptions.__dataclass_fields__:
                        raise ValueError(
                            f"unknown deployment override {key!r} for "
                            f"{dep_name!r}")
            if target.name in overrides:
                target = target.set_options(**overrides[target.name])
            # the static graph walk (not a controller diff) gives the
            # exact deployment set even when apps share children
            plans.append((name, app, target, overrides,
                          tree_names(target) | set(overrides)))

        # snapshot pre-PUT state; teardown happens LAST so a failed
        # deploy never destroys the previously-running apps
        needed = set().union(*(p[4] for p in plans)) if plans else set()
        new_names = {p[0] for p in plans}
        with _lock:
            prev_deployments = set()
            for info in _applications.values():
                prev_deployments |= set(info["deployments"])

        # pass 2 — deploy the new config
        deployed = []
        for name, app, target, overrides, dep_names in plans:
            serve.run(target, use_actors=use_actors, http=http, port=port)
            # apply overrides to already-deployed graph children too:
            # every option a root gets via set_options, not just
            # num_replicas
            ctrl = serve._get_controller()
            for dep_name, opts in overrides.items():
                if dep_name != target.name and dep_name in ctrl.deployments:
                    st = ctrl.deployments[dep_name]
                    for key, val in opts.items():
                        if key == "num_replicas":
                            st.scale_to(int(val))
                        elif hasattr(st.deployment.options, key):
                            setattr(st.deployment.options, key, val)
                        else:
                            raise ValueError(
                                f"unknown deployment override {key!r} "
                                f"for {dep_name!r}")
            with _lock:
                _applications[name] = {
                    "import_path": app["import_path"],
                    "route_prefix": app.get("route_prefix",
                                            f"/{target.name}"),
                    "deployments": sorted(dep_names),
                }
            deployed.append(name)

        # pass 3 — the new config is fully live: tear down whole stale
        # apps and obsolete deployments of re-configured apps
        with _lock:
            for name in list(_applications):
                if name not in new_names:
                    _applications.pop(name)
        obsolete = prev_deployments - needed
        if obsolete:
            ctrl = serve._get_controller()
            for dep in sorted(obsolete):
                if dep in ctrl.deployments:
                    serve.delete(dep)
        return deployed


def describe() -> dict:
    """Serve status document (reference: GET /api/serve/applications/
    → ServeInstanceDetails)."""
    from ray_tpu import serve
    status = serve.status()
    with _lock:
        apps = {name: dict(info) for name, info in _applications.items()}
    for info in apps.values():
        info["status"] = "RUNNING" if all(
            status.get(d, {}).get("replicas", 0) > 0
            for d in info["deployments"]) else "DEPLOYING"
        info["deployments"] = {
            d: status.get(d, {}) for d in info["deployments"]}
    return {"applications": apps,
            "proxy_location": serve.proxy_address(),
            "deployments": status}


def shutdown_all() -> None:
    from ray_tpu import serve
    with _apply_lock:
        serve.shutdown()
        with _lock:
            _applications.clear()


class ServeRestServer:
    """Standalone REST endpoint (the dashboard mounts the same handlers)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        outer_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, payload: Any):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path.rstrip("/") == "/api/serve/applications":
                    self._reply(200, describe())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_PUT(self):
                if self.path.rstrip("/") != "/api/serve/applications":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                n = int(self.headers.get("Content-Length", 0))
                try:
                    cfg = json.loads(self.rfile.read(n) or b"{}")
                    deployed = apply_config(cfg)
                    self._reply(200, {"deployed": deployed})
                except Exception as e:  # noqa: BLE001 - wire to client
                    self._reply(400, {"error": str(e)})

            def do_DELETE(self):
                if self.path.rstrip("/") == "/api/serve/applications":
                    shutdown_all()
                    self._reply(200, {})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self.address = f"http://{host}:{self.port}"
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="raytpu-serve-rest")
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
