"""Result of a training run (reference: python/ray/air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class Result:
    metrics: dict = field(default_factory=dict)     # last reported metrics
    checkpoint: Optional[Checkpoint] = None
    error: Optional[BaseException] = None
    path: Optional[str] = None                      # run directory
    metrics_history: list = field(default_factory=list)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        return self.checkpoint
