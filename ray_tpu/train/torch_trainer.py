"""TorchTrainer: data-parallel torch training over actor workers.

Reference capability: python/ray/train/torch/ — TorchTrainer
(torch/torch_trainer.py), TorchConfig/_TorchBackend
(torch/config.py:29,129: `_setup_torch_process_group` →
`dist.init_process_group(backend=...)` with a TCP rendezvous on the
rank-0 worker), plus `train.torch.prepare_model` (DDP wrap).

ray_tpu shape: torch here is a *host-side* framework (CPU build in this
image; the TPU compute path is jax) — so unlike JaxTrainer's
in-process SPMD, TorchTrainer runs the reference architecture for
real: N worker ACTORS, a gloo process group rendezvoused over TCP,
per-worker session reporting gathered by the driver, rank-0
checkpoints through the shared run dir. This is the migration surface
for users arriving with torch training loops.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train.trainer import BaseTrainer, TrainingFailedError


@dataclass
class TorchConfig:
    """Process-group knobs (reference: torch/config.py:29 TorchConfig)."""
    backend: str = "gloo"          # CPU image: gloo; nccl has no GPUs here
    init_timeout_s: float = 120.0


class _TorchWorker:
    """One training worker actor (reference: the WorkerGroup actor in
    train/_internal/worker_group.py:92 + _TorchBackend.on_start).

    Two-phase startup like the reference: rank 0 reports its own
    address + a probed port (`master_address`, torch/config.py:69
    `_setup_torch_process_group` rendezvous on the rank-0 WORKER, not
    the driver — workers may land on other nodes), then every rank's
    `setup_pg` joins the group."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._ckpt_payload = None

    def master_address(self) -> tuple:
        """Rank-0's reachable host + a free port (bind-probe; the small
        release-to-bind race matches the reference's get_address)."""
        host = socket.gethostbyname(socket.gethostname())
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return host, port

    def setup_pg(self, master_addr: str, master_port: int, backend: str,
                 timeout_s: float) -> bool:
        os.environ["MASTER_ADDR"] = master_addr
        os.environ["MASTER_PORT"] = str(master_port)
        os.environ["RANK"] = str(self.rank)
        os.environ["WORLD_SIZE"] = str(self.world_size)
        import datetime

        import torch.distributed as dist
        if dist.is_initialized():
            dist.destroy_process_group()
        dist.init_process_group(
            backend=backend, rank=self.rank,
            world_size=self.world_size,
            init_method=f"tcp://{master_addr}:{master_port}",
            timeout=datetime.timedelta(seconds=timeout_s))
        return True

    def run(self, loop: Callable, config: dict,
            restore_payload) -> dict:
        """Execute the user loop inside a session; returns
        {reports, checkpoint} for the driver to merge."""
        from ray_tpu.train import session as _s
        worker = self

        def ckpt_cb(data):
            worker._ckpt_payload = data   # kept worker-side; rank 0's
            return None                   # payload rides the return value

        latest = (Checkpoint.from_dict(restore_payload)
                  if restore_payload is not None else None)
        st = _s._start(world_rank=self.rank, world_size=self.world_size,
                       checkpoint_cb=ckpt_cb, latest_checkpoint=latest)
        try:
            if loop.__code__.co_argcount == 0:
                loop()
            else:
                loop(dict(config))
        except StopIteration:
            pass
        finally:
            _s._end()
        reports = [{k: v for k, v in r.items()
                    if k != "_checkpoint_path"} for r in st.results]
        return {"reports": reports,
                "checkpoint": self._ckpt_payload if self.rank == 0
                else None}

    def shutdown(self):
        import torch.distributed as dist
        if dist.is_initialized():
            dist.destroy_process_group()
        return True


def prepare_model(model):
    """Wrap in DDP when a process group is active (reference:
    train/torch/train_loop_utils.py prepare_model)."""
    import torch.distributed as dist
    if dist.is_available() and dist.is_initialized() \
            and dist.get_world_size() > 1:
        from torch.nn.parallel import DistributedDataParallel
        return DistributedDataParallel(model)
    return model


class TorchTrainer(BaseTrainer):
    """(reference: train/torch/torch_trainer.py TorchTrainer)"""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._torch_config = torch_config or TorchConfig()

    @property
    def _num_workers(self) -> int:
        sc = self.scaling_config
        if sc.num_workers is not None:
            return sc.num_workers
        dp = sc.mesh.get("dp", 1)
        return dp if dp > 0 else 1

    def _attempt(self) -> None:
        import ray_tpu
        from ray_tpu.train import session as _session
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self._torch_config
        world = self._num_workers
        Worker = ray_tpu.remote(_TorchWorker)
        workers = [Worker.remote(r, world) for r in range(world)]
        st = _session._state()
        st.world_size = world
        restore = st.latest_checkpoint
        restore_payload = restore.to_dict() if restore is not None else None
        try:
            # rendezvous on the rank-0 WORKER's address (it may be on a
            # different node than the driver); then all ranks join
            addr, port = ray_tpu.get(workers[0].master_address.remote(),
                                     timeout=tc.init_timeout_s)
            ray_tpu.get([w.setup_pg.remote(addr, port, tc.backend,
                                           tc.init_timeout_s)
                         for w in workers],
                        timeout=tc.init_timeout_s + 60)
            refs = [w.run.remote(self._loop, self._loop_config,
                                 restore_payload) for w in workers]
            # training runs as long as it runs — no duration cap; worker
            # death surfaces as a task error and triggers fit()'s retry
            outs = ray_tpu.get(refs, timeout=None)
            # merge: stream rank-0 reports through the driver session so
            # fit()'s manager sees metrics/checkpoints in order
            rank0 = outs[0]
            n = len(rank0["reports"])
            for i, metrics in enumerate(rank0["reports"]):
                is_last = i == n - 1
                ck = rank0["checkpoint"] if is_last else None
                _session.report(metrics, checkpoint=ck)
        finally:
            for w in workers:
                try:
                    ray_tpu.get(w.shutdown.remote(), timeout=30)
                except Exception:  # noqa: BLE001
                    pass
                try:
                    ray_tpu.kill(w)
                except Exception:  # noqa: BLE001
                    pass
