"""Sharded train-step factory: the compiled heart of the train layer.

Reference contrast: the reference's gradient path is torch DDP allreduce
set up out-of-band (python/ray/train/torch/config.py:113
dist.init_process_group) — the framework never sees the math.  Here the
*entire* step (fwd, bwd, optimizer, collectives) is ONE jitted SPMD
program: params/opt-state sharded by logical-axis rules, batch sharded
over the data axes, XLA inserts psum/reduce-scatter over ICI.  Buffers
are donated so params/opt state update in place in HBM.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from ray_tpu.parallel.mesh import batch_sharding, replicated
from ray_tpu.parallel.sharding import (DEFAULT_LLM_RULES, Rules,
                                       tree_shardings)


@dataclass
class TrainState:
    """Minimal train state pytree (step, params, opt_state)."""
    step: Any
    params: Any
    opt_state: Any


jax.tree_util.register_dataclass(
    TrainState, data_fields=["step", "params", "opt_state"], meta_fields=[])


def state_shardings(mesh: Mesh, params_logical: Any, rules: Rules,
                    params: Any, tx: optax.GradientTransformation):
    """Shardings for a TrainState: params by rules; opt-state subtrees
    that mirror the params pytree (adam mu/nu, momentum, …) get the same
    shardings; everything else (counts, scalars) replicated.  Matching
    is STRUCTURAL, not by shape — two same-shaped params with different
    rules must keep their own shardings."""
    p_sh = tree_shardings(params_logical, rules, mesh)
    rep = replicated(mesh)
    opt_state = jax.eval_shape(tx.init, params)
    p_struct = jax.tree.structure(params)

    # If params is one bare array, every leaf matches p_struct
    # structurally — require a shape match too, so scalar opt-state
    # leaves (adam counts) don't inherit a rank>0 partition spec.
    p_is_leaf = p_struct == jax.tree.structure(0)

    def map_node(node):
        if jax.tree.structure(node) == p_struct:
            if not p_is_leaf or getattr(node, "shape", None) == params.shape:
                return p_sh
        if isinstance(node, tuple) and not hasattr(node, "shape"):
            mapped = [map_node(c) for c in node]
            return (type(node)(*mapped) if hasattr(node, "_fields")
                    else tuple(mapped))
        if isinstance(node, list):
            return [map_node(c) for c in node]
        return jax.tree.map(lambda _: rep, node)

    o_sh = map_node(opt_state)
    return TrainState(step=rep, params=p_sh, opt_state=o_sh)


def make_train_step(loss_fn: Callable, tx: optax.GradientTransformation, *,
                    mesh: Optional[Mesh] = None,
                    params_logical: Any = None,
                    rules: Rules = DEFAULT_LLM_RULES,
                    donate: bool = True):
    """Build ``(init_fn, step_fn)``.

    loss_fn(params, batch) -> scalar (already closed over model config;
    pass mesh/rules inside if the model constrains activations).

    init_fn(params) -> sharded TrainState (device_put with the rule
    shardings when a mesh is given).
    step_fn(state, batch) -> (state, metrics) — jitted, donated.
    """
    st_sh = None

    def init_fn(params):
        nonlocal st_sh
        if mesh is not None and params_logical is not None:
            st_sh = state_shardings(mesh, params_logical, rules, params, tx)
            params = jax.tree.map(
                lambda x, s: jax.device_put(x, s), params, st_sh.params)
        elif mesh is not None:
            # no logical rules: pure data parallelism — replicate the
            # whole state over the mesh.  Mandatory in multi-process
            # (every array must span the global mesh), and the correct
            # DP placement in-process too.
            rep = replicated(mesh)
            opt_shape = jax.eval_shape(tx.init, params)
            st_sh = TrainState(
                step=rep,
                params=jax.tree.map(lambda _: rep, params),
                opt_state=jax.tree.map(lambda _: rep, opt_shape))
            params = jax.tree.map(lambda x: jax.device_put(x, rep), params)
        else:
            # defensive copy: the step donates its state, and donating
            # buffers the CALLER still holds would delete them under it
            params = jax.tree.map(
                lambda x: x.copy() if isinstance(x, jax.Array)
                else jnp.asarray(x), params)
        opt_state = jax.jit(
            tx.init,
            out_shardings=st_sh.opt_state if st_sh else None)(params)
        step0 = jnp.zeros((), jnp.int32)
        if mesh is not None:
            step0 = jax.device_put(step0, replicated(mesh))
        return TrainState(step=step0, params=params, opt_state=opt_state)

    def _step(state: TrainState, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(step=state.step + 1, params=params,
                           opt_state=opt_state),
                {"loss": loss, "grad_norm": gnorm})

    if mesh is not None:
        # jit lazily so init_fn can run first and fix shardings
        compiled = {}

        def step_fn(state, batch):
            if "fn" not in compiled:
                b_sh = jax.tree.map(lambda _: batch_sharding(mesh), batch)
                compiled["fn"] = jax.jit(
                    _step,
                    in_shardings=(st_sh, b_sh) if st_sh else None,
                    donate_argnums=(0,) if donate else ())
            return compiled["fn"](state, batch)
    else:
        step_fn = jax.jit(_step, donate_argnums=(0,) if donate else ())

    return init_fn, step_fn


def shard_batch(batch, mesh: Mesh):
    """Host batch → device batch sharded over the data axes.

    Multi-process (one jax process per TPU host): every process holds
    the SAME global host batch (deterministic iterators), carves out the
    rows its local devices own, and assembles the global array with
    ``jax.make_array_from_process_local_data`` — the SPMD data-feed
    pattern the scaling playbook prescribes; no host ever materializes
    another host's shard on device."""
    sh = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sh), batch)

    import numpy as np

    def shard_one(x):
        if not hasattr(x, "shape") or x.ndim == 0:
            return jax.device_put(x, sh)
        global_shape = x.shape
        # rows owned by this process under the data-axis sharding;
        # ownership may be non-contiguous on interleaved device meshes,
        # so concatenate the owned ranges in index order
        lo = global_shape[0]
        idx = sh.addressable_devices_indices_map(global_shape)
        rows = sorted({(s[0].start or 0, s[0].stop if s[0].stop is not None
                        else lo) for s in idx.values()})
        parts = [x[a:b] for a, b in rows]
        local = parts[0] if len(parts) == 1 else np.concatenate(parts)
        return jax.make_array_from_process_local_data(
            sh, local, global_shape)
    return jax.tree.map(shard_one, batch)


def train_step_1f1b(cfg, mesh: Mesh, *, batch_n: int, seq: int,
                    check_parity: bool = True) -> float:
    """One GPT train pass through the fused 1F1B pipeline schedule
    (parallel/pipeline_1f1b.py): embedding runs outside under jax.vjp,
    the layer stack rides the 1F1B scan, the loss tail (final norm +
    head + CE) is folded into the last stage's backward.  Asserts loss
    parity with the plain single-device loss and that gradients flow to
    EVERY parameter (tied embeddings get both the embed- and head-side
    contributions).  Returns the loss."""
    from jax import lax

    from ray_tpu.models import gpt
    from ray_tpu.parallel.pipeline_1f1b import pipeline_value_and_grads_1f1b

    S = mesh.shape["pp"]
    M = cfg.pp_microbatches or 2 * S
    assert batch_n % M == 0, (batch_n, M)
    params = gpt.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jnp.zeros((batch_n, seq + 1), jnp.int32)
    inp, tgt = tokens[:, :-1], tokens[:, 1:]
    body = gpt._layer_scan_body(cfg, mesh, DEFAULT_LLM_RULES)
    tied = cfg.tie_embeddings

    def stage_fn(lp, x):
        (x, _), _ = lax.scan(body, (x, jnp.zeros((), jnp.float32)), lp)
        return x

    def last_fn(tp, x, y):
        # reuse the model's own head (tie-embeddings convention, logit
        # dtype policy); mesh=None — constraints don't apply inside the
        # pipeline's manual region
        logits = gpt._head(tp, x, cfg, None, DEFAULT_LLM_RULES)
        logz = jax.nn.logsumexp(logits, -1)
        gold = jnp.take_along_axis(logits, y[..., None], -1)[..., 0]
        return jnp.mean(logz - gold)

    def embed_fn(ep, toks):
        return gpt._embed(ep, toks, cfg, None, DEFAULT_LLM_RULES)

    tail_keys = ["ln_f_scale", "ln_f_bias"] + \
        (["wte"] if tied else ["lm_head"])

    @jax.jit
    def step(params):
        eparams = {"wte": params["wte"], "wpe": params["wpe"]}
        tail = {k: params[k] for k in tail_keys}
        x, embed_vjp = jax.vjp(lambda ep: embed_fn(ep, inp), eparams)
        mb = batch_n // M
        x_mb = x.reshape(M, mb, seq, cfg.d_model)
        y_mb = tgt.reshape(M, mb, seq)
        loss, d_layers, d_tail, d_x = pipeline_value_and_grads_1f1b(
            stage_fn, last_fn, x_mb, y_mb, params["layers"], tail,
            mesh=mesh)
        (d_embed,) = embed_vjp(
            d_x.reshape(batch_n, seq, cfg.d_model).astype(x.dtype))
        grads = {"layers": d_layers, "wpe": d_embed["wpe"],
                 "ln_f_scale": d_tail["ln_f_scale"],
                 "ln_f_bias": d_tail["ln_f_bias"]}
        if tied:
            grads["wte"] = d_embed["wte"] + d_tail["wte"]
        else:
            grads["wte"] = d_embed["wte"]
            grads["lm_head"] = d_tail["lm_head"]
        return loss, grads

    with mesh:
        loss, grads = step(params)
        jax.block_until_ready(grads)
    gnorm = float(optax.global_norm(grads))
    assert jnp.isfinite(gnorm) and gnorm > 0.0, gnorm
    if check_parity:
        ref = float(gpt.loss_fn(params, {"tokens": tokens}, cfg))
        assert abs(float(loss) - ref) < 1e-3 + 1e-3 * abs(ref), (
            f"1F1B loss {float(loss)} != reference {ref}")
    return float(loss)
