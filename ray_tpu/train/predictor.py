"""Predictors: checkpoint → inference, single-batch and over Datasets.

Reference capability: python/ray/train/predictor.py Predictor +
batch_predictor.py BatchPredictor (map_batches over a Dataset with the
model broadcast once per worker) + the framework predictors
(torch_predictor.py etc.).  TPU shape: JaxPredictor jits the apply
function once and feeds device batches; BatchPredictor rides
Dataset.map_batches, with the actor-pool compute strategy giving the
reference's actor-based prediction path.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint


class Predictor:
    """Base: subclasses implement predict(batch) → batch
    (column dicts in, column dicts out)."""

    def predict(self, batch: dict) -> dict:
        raise NotImplementedError

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, **kw) -> "Predictor":
        raise NotImplementedError


class JaxPredictor(Predictor):
    """Wraps a pure apply_fn(params, batch_array) → predictions.

    feature_column selects the input column (default "x"); output lands
    in "predictions".  The apply is jitted once; batches stream through
    one device transfer each.
    """

    def __init__(self, apply_fn: Callable, params: Any, *,
                 feature_column: str = "x",
                 output_column: str = "predictions"):
        import jax
        self._apply = jax.jit(apply_fn)
        self._params = params
        self.feature_column = feature_column
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint, *,
                        apply_fn: Callable, **kw) -> "JaxPredictor":
        data = checkpoint.to_dict()
        params = data.get("params", data)
        return cls(apply_fn, params, **kw)

    def predict(self, batch: dict) -> dict:
        import jax.numpy as jnp
        x = jnp.asarray(batch[self.feature_column])
        out = self._apply(self._params, x)
        result = {k: v for k, v in batch.items()
                  if k != self.feature_column}
        if isinstance(out, tuple):
            result[self.output_column] = np.asarray(out[0])
        else:
            result[self.output_column] = np.asarray(out)
        return result


class SklearnPredictor(Predictor):
    """(reference: train/sklearn/sklearn_predictor.py)"""

    def __init__(self, estimator, *, feature_columns: Optional[list] = None,
                 output_column: str = "predictions"):
        self.estimator = estimator
        self.feature_columns = feature_columns
        self.output_column = output_column

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        **kw) -> "SklearnPredictor":
        data = checkpoint.to_dict()
        # SklearnTrainer stores the training feature order — predicting
        # with any other column set/order is wrong
        kw.setdefault("feature_columns", data.get("feature_columns"))
        return cls(data["estimator"], **kw)

    def predict(self, batch: dict) -> dict:
        cols = self.feature_columns or list(batch)
        X = np.column_stack([np.asarray(batch[c]) for c in cols])
        out = dict(batch)
        out[self.output_column] = self.estimator.predict(X)
        return out


class BatchPredictor:
    """Dataset-scale prediction (reference:
    train/batch_predictor.py BatchPredictor.predict)."""

    def __init__(self, predictor: Predictor):
        self._predictor = predictor

    @classmethod
    def from_checkpoint(cls, checkpoint: Checkpoint,
                        predictor_cls: type, **kw) -> "BatchPredictor":
        return cls(predictor_cls.from_checkpoint(checkpoint, **kw))

    def predict(self, dataset, *, batch_size: int = 256,
                compute: str = "inline", num_actors: int = 2):
        """→ Dataset of predictions.  compute="actors" fans blocks over
        an actor pool (model shipped once per actor, the reference's
        actor-prediction strategy)."""
        if compute not in ("inline", "tasks", "actors"):
            raise ValueError(f"compute must be inline|tasks|actors, "
                             f"got {compute!r}")
        pred = self._predictor
        ds = dataset.map_batches(pred.predict, batch_size=batch_size)
        return ds.materialize(parallelism=compute, num_actors=num_actors)
