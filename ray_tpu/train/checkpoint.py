"""Checkpoints: dict ⇄ directory, async sharded writes for jax pytrees.

Reference capability: air.Checkpoint (python/ray/air/checkpoint.py —
dict/dir/URI interconvertible) + Tune's CheckpointManager
(tune/execution/checkpoint_manager.py).  TPU delta (SURVEY.md §7 delta 4):
checkpointing is on the FT critical path (slice loss ⇒ restart-from-
checkpoint), so writes are (a) sharded — each host writes only the
addressable shards it owns via orbax — and (b) async — the train loop
donates a snapshot and keeps stepping while the write drains.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
from typing import Any, Optional

import jax
import numpy as np


def _to_host(tree):
    """Device → host copy (blocks until transfer done, not until write)."""
    return jax.tree.map(
        lambda x: np.asarray(x) if isinstance(x, jax.Array) else x, tree)


class Checkpoint:
    """A checkpoint is a directory; dict payloads are pickled into it.

    ``from_dict``/``to_dict`` mirror the reference's interconversion; jax
    pytrees ride through as host numpy (zero surprise on restore —
    restore + device_put with the target sharding re-shards to any mesh,
    which is how elastic restarts across different slice shapes work).
    """

    PAYLOAD = "payload.pkl"
    META = "ckpt_meta.json"

    def __init__(self, path: str):
        self.path = path

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict, path: Optional[str] = None) -> "Checkpoint":
        path = path or tempfile.mkdtemp(prefix="ray_tpu_ckpt_")
        os.makedirs(path, exist_ok=True)
        host = _to_host(data)
        tmp = os.path.join(path, cls.PAYLOAD + ".tmp")
        with open(tmp, "wb") as f:
            pickle.dump(host, f, protocol=pickle.HIGHEST_PROTOCOL)
        os.replace(tmp, os.path.join(path, cls.PAYLOAD))
        with open(os.path.join(path, cls.META), "w") as f:
            json.dump({"format": "dict", "time": time.time()}, f)
        return cls(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    # -- accessors ---------------------------------------------------------

    def to_dict(self) -> dict:
        with open(os.path.join(self.path, self.PAYLOAD), "rb") as f:
            return pickle.load(f)

    def to_directory(self, dest: Optional[str] = None) -> str:
        if dest is None or os.path.abspath(dest) == os.path.abspath(self.path):
            return self.path
        shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    def __repr__(self):
        return f"Checkpoint({self.path!r})"


class AsyncCheckpointer:
    """Snapshot-then-write-in-background (one writer thread, latest-wins
    queue of depth 1 — dropping intermediate snapshots is safe because a
    checkpoint is a restart point, not a log)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: Optional[tuple] = None
        self._thread: Optional[threading.Thread] = None
        self._running = False     # drain-loop liveness, guarded by _lock
        self._error: Optional[BaseException] = None
        self.last_path: Optional[str] = None

    def save(self, data: dict, path: str) -> None:
        host = _to_host(data)  # synchronous D2H; disk write is async
        with self._lock:
            self._pending = (host, path)
            # _running flips false only under this lock (in _drain), so
            # a save racing the drain thread's exit always restarts it —
            # is_alive() alone races with the loop's decision to return
            if not self._running:
                self._running = True
                self._thread = threading.Thread(target=self._drain,
                                                daemon=True)
                self._thread.start()

    def _drain(self):
        while True:
            with self._lock:
                if self._pending is None:
                    self._running = False
                    return
                host, path = self._pending
                self._pending = None
            try:
                Checkpoint.from_dict(host, path)
                self.last_path = path
            except BaseException as e:  # surfaced on wait()
                self._error = e

    def wait(self):
        while True:
            with self._lock:
                t = self._thread
                busy = self._running or self._pending is not None
            if not busy:
                break
            if t is not None:
                t.join(timeout=0.05)
        if self._error is not None:
            err, self._error = self._error, None
            raise err


class CheckpointManager:
    """Keeps the last N checkpoints under a run dir (reference:
    tune/execution/checkpoint_manager.py)."""

    def __init__(self, root: str, num_to_keep: Optional[int] = None,
                 async_write: bool = True):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.num_to_keep = num_to_keep
        self._seq = 0
        self._kept: list[str] = list(self._existing())
        self._async = AsyncCheckpointer() if async_write else None

    def _existing(self):
        if not os.path.isdir(self.root):
            return []
        out = sorted(d for d in os.listdir(self.root)
                     if d.startswith("checkpoint_"))
        if out:
            self._seq = int(out[-1].split("_")[1]) + 1
        return (os.path.join(self.root, d) for d in out)

    def save(self, data: dict) -> str:
        path = os.path.join(self.root, f"checkpoint_{self._seq:06d}")
        self._seq += 1
        if self._async is not None:
            self._async.save(data, path)
        else:
            Checkpoint.from_dict(data, path)
        self._kept.append(path)
        while (self.num_to_keep is not None
               and len(self._kept) > self.num_to_keep):
            victim = self._kept.pop(0)
            if self._async is not None:
                self._async.wait()
            shutil.rmtree(victim, ignore_errors=True)
        return path

    def latest(self) -> Optional[Checkpoint]:
        self.flush()
        # re-scan the directory: in multi-host runs, rank-0 members write
        # checkpoints here from ANOTHER process (reference: workers
        # persist to storage_path; the driver discovers them on restore)
        on_disk = sorted(
            os.path.join(self.root, d) for d in os.listdir(self.root)
            if d.startswith("checkpoint_")) if os.path.isdir(self.root) \
            else []
        for path in on_disk:
            if path not in self._kept:
                self._kept.append(path)
        self._kept.sort()
        if self._kept:
            last = self._kept[-1]
            self._seq = max(self._seq,
                            int(os.path.basename(last).split("_")[1]) + 1)
        for path in reversed(self._kept):
            if os.path.exists(os.path.join(path, Checkpoint.PAYLOAD)):
                return Checkpoint(path)
        return None

    def flush(self):
        if self._async is not None:
            self._async.wait()
