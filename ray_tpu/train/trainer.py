"""Trainers: gang-scheduled SPMD training with restart-based FT.

Reference capability: train.DataParallelTrainer
(python/ray/train/data_parallel_trainer.py:56) + BackendExecutor
(train/_internal/backend_executor.py:43 — placement group, worker gang,
restart loop :571).  TPU shape (SURVEY.md §7 M4): the worker group is a
TpuGang (one SPMD program over a named mesh), the "backend" is jax
itself — there is no process-group setup step because collectives are
compiled into the program.
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from typing import Any, Callable, Optional

import jax

from ray_tpu.parallel.gang import GangConfig, TpuGang
from ray_tpu.train import session as _session
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.result import Result

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


class BaseTrainer:
    """fit() drives the run; subclasses define what one attempt does
    (reference: train/base_trainer.py:344 fit — whose delegation *into
    Tune* for a 1-trial run we deliberately do not copy: a plain train
    run should not drag in a tuner; instead Tune wraps trainers, see
    ray_tpu.tune)."""

    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    # subclass hook: one full training attempt in an active session
    def _attempt(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        run_dir = self.run_config.resolved_storage_path()
        os.makedirs(run_dir, exist_ok=True)
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(run_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            async_write=ckpt_cfg.async_write)
        max_failures = self.run_config.failure_config.max_failures
        restore = self.resume_from_checkpoint or manager.latest()

        attempt, error = 0, None
        results: list = []
        while True:
            st = _session._start(
                world_rank=0,
                world_size=self.scaling_config.num_hosts,
                checkpoint_cb=lambda data: manager.save(data),
                latest_checkpoint=restore)
            try:
                self._attempt()
                error = None
                break
            except StopIteration:
                error = None
                break
            except Exception as e:  # restart-based FT
                error = e
                attempt += 1
                logger.warning("training attempt %d failed: %s", attempt, e)
                if attempt > max_failures:
                    break
                manager.flush()
                restore = manager.latest()  # rebuild from last checkpoint
            finally:
                results.extend(st.results)
                _session._end()

        manager.flush()
        metrics = results[-1] if results else {}
        res = Result(metrics=metrics, checkpoint=manager.latest(),
                     error=error, path=run_dir, metrics_history=results)
        if error is not None and max_failures >= 0:
            raise TrainingFailedError(
                f"Training failed after {attempt} attempt(s): {error}\n"
                + "".join(traceback.format_exception(error))) from error
        return res


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker(config)`` on the gang
    (reference: data_parallel_trainer.py:56; training_loop :347).

    Single-host: the loop runs in-process with the gang's mesh active —
    jax is single-controller per host, so there is no worker hop and no
    pickling of arrays.  Multi-host: one member process per host executes
    the same loop (SPMD), coordinated via jax.distributed.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[dict] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._datasets = datasets or {}
        self._gang: Optional[TpuGang] = None

    @property
    def gang(self) -> TpuGang:
        if self._gang is None:
            sc = self.scaling_config
            self._gang = TpuGang(GangConfig(
                mesh_axes=dict(sc.mesh), num_hosts=sc.num_hosts,
                use_cpu_devices=sc.use_cpu_devices))
        return self._gang

    def _attempt(self) -> None:
        gang = self.gang
        st = _session._state()
        st.world_size = gang.num_hosts
        cfg = dict(self._loop_config)
        if self._datasets:
            cfg["datasets"] = {
                name: ds.iter_batches_sharded(gang.mesh)
                if hasattr(ds, "iter_batches_sharded") else ds
                for name, ds in self._datasets.items()}
        with gang.mesh:
            if self._loop.__code__.co_argcount == 0:
                self._loop()
            else:
                self._loop(cfg)
