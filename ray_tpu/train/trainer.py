"""Trainers: gang-scheduled SPMD training with restart-based FT.

Reference capability: train.DataParallelTrainer
(python/ray/train/data_parallel_trainer.py:56) + BackendExecutor
(train/_internal/backend_executor.py:43 — placement group, worker gang,
restart loop :571).  TPU shape (SURVEY.md §7 M4): the worker group is a
TpuGang (one SPMD program over a named mesh), the "backend" is jax
itself — there is no process-group setup step because collectives are
compiled into the program.
"""

from __future__ import annotations

import logging
import os
import time
import traceback
from typing import Any, Callable, Optional

import jax

from ray_tpu.parallel.gang import GangConfig, MultiHostGang, TpuGang
from ray_tpu.train import ingest as _ingest
from ray_tpu.train import session as _session
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.result import Result

logger = logging.getLogger("ray_tpu.train")


class TrainingFailedError(RuntimeError):
    pass


class BaseTrainer:
    """fit() drives the run; subclasses define what one attempt does
    (reference: train/base_trainer.py:344 fit — whose delegation *into
    Tune* for a 1-trial run we deliberately do not copy: a plain train
    run should not drag in a tuner; instead Tune wraps trainers, see
    ray_tpu.tune)."""

    def __init__(self, *, scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    # subclass hook: one full training attempt in an active session
    def _attempt(self) -> None:
        raise NotImplementedError

    def fit(self) -> Result:
        run_dir = self.run_config.resolved_storage_path()
        os.makedirs(run_dir, exist_ok=True)
        ckpt_cfg = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(run_dir, "checkpoints"),
            num_to_keep=ckpt_cfg.num_to_keep,
            async_write=ckpt_cfg.async_write)
        max_failures = self.run_config.failure_config.max_failures
        restore = self.resume_from_checkpoint or manager.latest()

        attempt, error = 0, None
        results: list = []
        while True:
            st = _session._start(
                world_rank=0,
                world_size=self.scaling_config.num_hosts,
                checkpoint_cb=lambda data: manager.save(data),
                latest_checkpoint=restore)
            try:
                self._attempt()
                error = None
                break
            except StopIteration:
                error = None
                break
            except Exception as e:  # restart-based FT
                error = e
                attempt += 1
                logger.warning("training attempt %d failed: %s", attempt, e)
                if attempt > max_failures:
                    break
                manager.flush()
                restore = manager.latest()  # rebuild from last checkpoint
            finally:
                results.extend(st.results)
                _session._end()

        manager.flush()
        metrics = results[-1] if results else {}
        res = Result(metrics=metrics, checkpoint=manager.latest(),
                     error=error, path=run_dir, metrics_history=results)
        if error is not None and max_failures >= 0:
            raise TrainingFailedError(
                f"Training failed after {attempt} attempt(s): {error}\n"
                + "".join(traceback.format_exception(error))) from error
        return res


class DataParallelTrainer(BaseTrainer):
    """Runs ``train_loop_per_worker(config)`` on the gang
    (reference: data_parallel_trainer.py:56; training_loop :347).

    Single-host: the loop runs in-process with the gang's mesh active —
    jax is single-controller per host, so there is no worker hop and no
    pickling of arrays.  Multi-host: one member process per host executes
    the same loop (SPMD), coordinated via jax.distributed.
    """

    def __init__(self, train_loop_per_worker: Callable,
                 *, train_loop_config: Optional[dict] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 datasets: Optional[dict] = None,
                 dataset_config: Optional[dict] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._datasets = datasets or {}
        # streamed-ingest knobs for multi-host datasets= (train/ingest.py):
        # global_batch_size (default 32), epochs (1), byte_budget (None =
        # byte-derived from the object store at spool time)
        self._dataset_config = dict(dataset_config or {})
        self._ingest_attempt = -1
        self._gang: Optional[TpuGang] = None
        # set by an elastic shrink so the immediately following RESUME
        # attempt runs at the reduced size; replacements are re-admitted
        # only at the NEXT re-gang boundary after that
        self._elastic_shrunk = False

    @property
    def gang(self):
        if self._gang is None:
            sc = self.scaling_config
            if sc.num_hosts > 1:
                # one member process per host, co-initialized through
                # jax.distributed (reference: backend_executor.py:94)
                self._gang = MultiHostGang(
                    sc.num_hosts,
                    cpu_backend=sc.use_cpu_devices,
                    devices_per_member=sc.devices_per_host,
                    num_tpus_per_member=sc.num_tpus_per_host,
                    resources_per_member=sc.resources_per_host)
            else:
                self._gang = TpuGang(GangConfig(
                    mesh_axes=dict(sc.mesh), num_hosts=sc.num_hosts,
                    use_cpu_devices=sc.use_cpu_devices))
        return self._gang

    def _attempt(self) -> None:
        gang = self.gang
        if isinstance(gang, MultiHostGang):
            self._attempt_multihost(gang)
            return
        st = _session._state()
        st.world_size = gang.num_hosts
        cfg = dict(self._loop_config)
        if self._datasets:
            cfg["datasets"] = {
                name: ds.iter_batches_sharded(gang.mesh)
                if hasattr(ds, "iter_batches_sharded") else ds
                for name, ds in self._datasets.items()}
        with gang.mesh:
            if self._loop.__code__.co_argcount == 0:
                self._loop()
            else:
                self._loop(cfg)

    def _elastic_recover(self, gang: MultiHostGang) -> bool:
        """Attempted in-place gang recovery after a failed multihost
        attempt.  True = the gang was re-formed from surviving member
        PROCESSES (shrunk to survivors, or re-admitted back toward the
        target size when this boundary saw no new deaths) and the next
        attempt should reuse it; False = fall back to full teardown +
        re-formation."""
        sc = self.scaling_config
        if not getattr(sc, "elastic", False):
            return False
        try:
            alive = gang.alive_ranks()
        except Exception:
            return False
        if len(alive) < max(1, getattr(sc, "min_hosts", 1)):
            return False
        try:
            if len(alive) < gang.num_members:
                logger.warning(
                    "elastic re-gang: %d/%d members survive; shrinking "
                    "and resuming from the latest checkpoint",
                    len(alive), gang.num_members)
                gang.reform(alive)
                self._elastic_shrunk = True
            elif gang.num_members < gang.target_members:
                # a re-gang boundary with no new deaths: re-admit
                # replacement members up to the target world size
                logger.warning(
                    "elastic re-gang: re-admitting %d replacement "
                    "member(s)",
                    gang.target_members - gang.num_members)
                gang.readmit()
            else:
                # all members alive (the failure was in the attempt, not
                # membership): rebuild the distributed world in place so
                # a poisoned collective runtime can't leak into the retry
                gang.reform(list(range(gang.num_members)))
        except Exception:
            logger.warning("elastic re-gang failed; falling back to full "
                           "gang restart", exc_info=True)
            return False
        self._gang = gang
        return True

    def _attempt_multihost(self, gang: MultiHostGang) -> None:
        """One SPMD attempt across gang members.

        Every member runs the SAME train loop over the global mesh; rank
        0 persists checkpoints straight into the run dir's checkpoint
        root (shared storage — the reference's workers likewise upload
        to storage_path), so the driver's CheckpointManager discovers
        them for restart-based FT.  A member death fails the attempt;
        with ``scaling_config.elastic`` the gang re-forms IN PLACE from
        the survivors (same pids) and fit() resumes from the latest
        checkpoint; otherwise — or when recovery fails — fit() re-forms
        a fresh gang (reference: backend_executor.py:571).

        ``datasets=`` rides the elastic data plane (train/ingest.py):
        the driver spools each dataset's streaming plan ONCE per fit
        (attempt restarts replay the same epoch order), members read
        positionally via ``session.get_dataset_shard(name)``, and every
        delivered range lands in a per-rank/attempt sample ledger.  A
        mid-epoch shrink or readmission changes ``world`` for the next
        attempt, and the pure-function sharding re-shards the stream at
        the resume step boundary with no data movement."""
        sc = self.scaling_config
        if (getattr(sc, "elastic", False) and not self._elastic_shrunk
                and gang.num_members < gang.target_members):
            # fresh attempt at a re-gang boundary (not the immediate
            # post-shrink resume): restore the target world size
            try:
                gang.readmit()
            except Exception:
                logger.warning("replacement re-admission failed; "
                               "continuing at world=%d", gang.num_members,
                               exc_info=True)
        self._elastic_shrunk = False
        st = _session._state()
        st.world_size = gang.num_members
        run_dir = self.run_config.resolved_storage_path()
        ckpt_dir = os.path.join(run_dir, "checkpoints")
        ckpt_cfg = self.run_config.checkpoint_config
        restore = st.latest_checkpoint
        # ship the checkpoint PATH, not the payload: members read it off
        # shared storage themselves (a multi-GB state dict must not ride
        # the driver's closure to every member)
        restore_path = restore.path if restore is not None else None
        mesh_axes = dict(self.scaling_config.mesh)
        world = gang.num_members
        loop_cfg = dict(self._loop_config)
        self._ingest_attempt += 1
        shard_specs = {}   # plain values only — this dict rides the closure
        for name, ds in self._datasets.items():
            dc = self._dataset_config
            spool_dir = os.path.join(run_dir, "ingest", name)
            man = _ingest.ensure_spooled(
                ds, spool_dir, byte_budget=dc.get("byte_budget"))
            shard_specs[name] = {
                "manifest": man.path,
                "global_batch": int(dc.get("global_batch_size", 32)),
                "epochs": int(dc.get("epochs", 1)),
                "ledger_dir": os.path.join(spool_dir, "ledger"),
                "attempt": self._ingest_attempt}
        trainer = self
        self._gang = None   # actor handles must not ride the closure

        def member_attempt(rank):
            import jax as _jax
            from ray_tpu.parallel.gang import GangConfig as _GC
            from ray_tpu.parallel.gang import TpuGang as _TG
            from ray_tpu.train import session as _s
            from ray_tpu.train.checkpoint import (Checkpoint as _Ck,
                                                  CheckpointManager as _CM)
            mgr = (_CM(ckpt_dir, num_to_keep=ckpt_cfg.num_to_keep,
                       async_write=False) if rank == 0 else None)

            def ckpt_cb(data):
                # SPMD lockstep: every rank reports the same checkpoint,
                # so this gather is a collective — rule-sharded arrays
                # that no single process fully addresses are assembled
                # on every host, then rank 0 alone persists
                from jax.experimental import multihost_utils as _mh

                def gather(x):
                    if isinstance(x, _jax.Array) \
                            and not x.is_fully_addressable:
                        # tiled: reassemble the GLOBAL value from shards
                        return _mh.process_allgather(x, tiled=True)
                    return x
                host = _jax.tree.map(gather, data)
                if mgr is not None:
                    mgr.save(host)

            latest = _Ck(restore_path) if restore_path else None
            mst = _s._start(world_rank=rank, world_size=world,
                            checkpoint_cb=ckpt_cb,
                            latest_checkpoint=latest)
            if shard_specs:
                from ray_tpu.train import ingest as _ing
                for nm, spec in shard_specs.items():
                    mst.dataset_shards[nm] = _ing.DatasetShard(
                        spec["manifest"], rank=rank, world=world,
                        global_batch=spec["global_batch"],
                        ledger_dir=spec["ledger_dir"],
                        attempt=spec["attempt"],
                        epochs=spec["epochs"], name=nm)
            stopped = False
            try:
                # the member-local gang spans the GLOBAL device set
                # (jax.distributed was initialized at member setup)
                trainer._gang = _TG(_GC(mesh_axes=mesh_axes,
                                        num_hosts=world))
                with trainer._gang.mesh:
                    if trainer._loop.__code__.co_argcount == 0:
                        trainer._loop()
                    else:
                        trainer._loop(dict(loop_cfg))
            except StopIteration:
                stopped = True   # clean stop must not count as a failure
            finally:
                _s._end()
            return {"rank": rank, "results": mst.results,
                    "stopped": stopped}

        try:
            outs = gang.run(member_attempt)
        except Exception:
            if self._elastic_recover(gang):
                # survivors re-formed in place; fit() restores from the
                # latest checkpoint and the next attempt reuses them
                raise
            # no survivors / reform failed: tear the gang down so the
            # retry forms a fresh one
            gang.shutdown()
            self._gang = None
            raise
        self._gang = gang
        for nm, spec in shard_specs.items():
            # fold the per-rank/attempt ledgers into the audit artifact
            # ("merged*" names are excluded from future merges)
            _ingest.merge_ledgers(
                spec["ledger_dir"],
                save_to=os.path.join(spec["ledger_dir"], "merged.json"))
        st.results.extend(outs[0]["results"])
