"""GBDT + sklearn trainers: tabular model training on host CPUs.

Reference capability: python/ray/train/gbdt_trainer.py (xgboost_ray/
lightgbm_ray actor trees) and train/sklearn/.  Trees are host-CPU work
in the two-tier model — no TPU involvement; the value here is the same
Trainer surface (fit → Result with metrics + checkpoint) over Datasets.
xgboost/lightgbm are not in the environment, so the default GBDT
implementation is sklearn's HistGradientBoosting (same algorithm family:
histogram gradient-boosted trees); pass ``use_xgboost=True`` to opt into
xgboost where it is installed.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import numpy as np

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.result import Result
from ray_tpu.train.trainer import BaseTrainer


def _to_xy(dataset, label_column: str, feature_columns=None):
    from ray_tpu.data import block as B
    full = B.concat(dataset._materialize())
    y = np.asarray(full[label_column])
    cols = feature_columns or [c for c in full if c != label_column]
    X = np.column_stack([np.asarray(full[c]) for c in cols])
    return X, y, cols


class SklearnTrainer(BaseTrainer):
    """(reference: train/sklearn/sklearn_trainer.py SklearnTrainer)"""

    def __init__(self, *, estimator, datasets: dict,
                 label_column: str,
                 feature_columns: Optional[list] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config)
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.feature_columns = feature_columns

    def fit(self) -> Result:
        import os
        t0 = time.perf_counter()
        X, y, cols = _to_xy(self.datasets["train"], self.label_column,
                            self.feature_columns)
        self.estimator.fit(X, y)
        metrics = {"fit_time_s": time.perf_counter() - t0,
                   "num_rows": len(y)}
        if "valid" in self.datasets:
            Xv, yv, _ = _to_xy(self.datasets["valid"], self.label_column,
                               cols)
            metrics["valid_score"] = float(self.estimator.score(Xv, yv))
        # checkpoint lands under the run directory like every trainer
        run_dir = self.run_config.resolved_storage_path()
        ck_dir = os.path.join(run_dir, "checkpoints", "final")
        os.makedirs(ck_dir, exist_ok=True)
        ck = Checkpoint.from_dict({"estimator": self.estimator,
                                   "feature_columns": cols},
                                  path=ck_dir)
        return Result(metrics=metrics, checkpoint=ck, path=run_dir)


class GBDTTrainer(SklearnTrainer):
    """Gradient-boosted decision trees (reference: gbdt_trainer.py —
    the XGBoostTrainer/LightGBMTrainer base).  Uses xgboost when
    importable, else sklearn HistGradientBoosting."""

    def __init__(self, *, datasets: dict, label_column: str,
                 objective: str = "classification",
                 params: Optional[dict] = None,
                 use_xgboost: bool = False,
                 feature_columns: Optional[list] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None):
        params = dict(params or {})
        est = self._make_estimator(objective, params, use_xgboost)
        super().__init__(estimator=est, datasets=datasets,
                         label_column=label_column,
                         feature_columns=feature_columns,
                         scaling_config=scaling_config,
                         run_config=run_config)

    @staticmethod
    def _make_estimator(objective: str, params: dict, use_xgboost: bool):
        # xgboost is explicit opt-in, not import-sniffed: the two
        # libraries interpret params differently (max_iter vs
        # n_estimators), and a silent swap would train a different model
        # depending on what happens to be installed
        if use_xgboost:  # pragma: no cover - xgboost absent here
            import xgboost
            cls = (xgboost.XGBClassifier if objective == "classification"
                   else xgboost.XGBRegressor)
            if "max_iter" in params:
                params["n_estimators"] = params.pop("max_iter")
            return cls(**params)
        from sklearn.ensemble import (HistGradientBoostingClassifier,
                                      HistGradientBoostingRegressor)
        cls = (HistGradientBoostingClassifier
               if objective == "classification"
               else HistGradientBoostingRegressor)
        return cls(**params)


class XGBoostTrainer(GBDTTrainer):
    """Name-compatible alias (reference: train/xgboost/xgboost_trainer.py)."""


class LightGBMTrainer(GBDTTrainer):
    """Name-compatible alias (reference: train/lightgbm/lightgbm_trainer.py)."""
