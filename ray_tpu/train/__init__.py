"""ray_tpu.train: gang-scheduled SPMD training (reference capability:
python/ray/train — SURVEY.md §2.4; TPU-first redesign per §7 M4)."""

from ray_tpu.train.checkpoint import (AsyncCheckpointer, Checkpoint,
                                      CheckpointManager)
from ray_tpu.train.config import (CheckpointConfig, FailureConfig, RunConfig,
                                  ScalingConfig)
from ray_tpu.train.gbdt_trainer import (GBDTTrainer, LightGBMTrainer,
                                        SklearnTrainer, XGBoostTrainer)
from ray_tpu.train.jax_trainer import JaxTrainer
from ray_tpu.train.predictor import (BatchPredictor, JaxPredictor,
                                     Predictor, SklearnPredictor)
from ray_tpu.train.result import Result
from ray_tpu.train.step import (TrainState, make_train_step, shard_batch,
                                state_shardings)
from ray_tpu.train.huggingface import TransformersTrainer
from ray_tpu.train.tensorflow import (TensorflowConfig, TensorflowTrainer,
                                      build_tf_config)
from ray_tpu.train.horovod import (HorovodConfig, HorovodTrainer,
                                   build_horovod_env)
from ray_tpu.train.torch_trainer import (TorchConfig, TorchTrainer,
                                         prepare_model)
from ray_tpu.train.trainer import (BaseTrainer, DataParallelTrainer,
                                   TrainingFailedError)
from ray_tpu.train import session
from ray_tpu.train.ingest import (DatasetShard, SampleLedger, merge_ledgers,
                                  shard_range, validate_ledger)

__all__ = [
    "Checkpoint", "CheckpointManager", "AsyncCheckpointer",
    "ScalingConfig", "RunConfig", "FailureConfig", "CheckpointConfig",
    "Result", "TrainState", "make_train_step", "shard_batch",
    "state_shardings", "BaseTrainer", "DataParallelTrainer", "JaxTrainer",
    "TrainingFailedError", "session", "GBDTTrainer", "SklearnTrainer",
    "XGBoostTrainer", "LightGBMTrainer", "Predictor", "JaxPredictor",
    "SklearnPredictor", "BatchPredictor", "TorchTrainer", "TorchConfig",
    "prepare_model", "TransformersTrainer",
    "TensorflowTrainer", "TensorflowConfig", "build_tf_config",
    "HorovodTrainer", "HorovodConfig", "build_horovod_env",
    "DatasetShard", "SampleLedger", "merge_ledgers", "shard_range",
    "validate_ledger",
]

from ray_tpu import usage_stats as _usage_stats
_usage_stats.record_library_usage("train")
