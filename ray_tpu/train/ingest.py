"""Streamed-ingest training data plane: epoch spool + elastic shards +
exactly-once sample ledger.

The multihost trainer can't ship a live Dataset iterator into gang
member processes (the driver owns the object store; members are
separate processes that may die and be replaced mid-epoch).  This
module makes ingest elastic with three pieces:

  * spool — the driver runs the dataset's STREAMING plan once
    (operator graph, in-plan shuffle, byte budgets — data/execution.py)
    and spools the resulting blocks to shared storage with a
    row-offset manifest.  Peak driver memory is the operator budgets,
    never the epoch; members read rows positionally.
  * pure-function sharding — the global sample range of step ``s`` is
    ``[s*B, (s+1)*B)`` of the spooled epoch order, and rank ``r`` of
    world ``W`` takes the near-even contiguous sub-slice
    (``shard_range``).  Data position is a function of (step, world)
    and nothing else, so a gang resize re-shards AUTOMATICALLY at the
    resume step boundary, and the per-step global batch is identical
    across any resize history — loss parity with an undisturbed run by
    construction.
  * ledger — every shard appends the step-stamped contiguous range it
    delivered to a per-rank, per-attempt JSON file (atomic rewrite).
    ``merge_ledgers`` folds the files; ``validate_ledger`` applies the
    checkpoint-consistency rule — for each step the HIGHEST attempt
    that delivered it is the surviving delivery, earlier attempts'
    entries for that step were rolled back with the step itself — and
    proves zero dropped / zero double-fed samples over the trained
    prefix.

Chaos: ``DatasetShard._chaos`` fires ``data_dispatch`` per step fetch
(ctx: {"shard", "rank", "step", "epoch"}) through the same
zero-overhead gate contract as every other plane
(analysis/hotpath_registry.py).
"""

from __future__ import annotations

import bisect
import glob
import json
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

import numpy as np

from ray_tpu.core import fault_injection as _fi
from ray_tpu.data import block as B


def _atomic_write_json(path: str, payload: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def shard_range(step: int, global_batch: int, rank: int,
                world: int) -> tuple:
    """Epoch-local sample range rank ``rank`` of ``world`` consumes at
    step ``step``: the near-even contiguous sub-slice of the step's
    global range ``[step*B, (step+1)*B)``.  Pure function of its
    arguments — THE re-sharding rule: after a resize, every rank of the
    new world computes its slice from the resume step alone, and the
    union over ranks is exactly the global range for any world size."""
    base = step * global_batch
    per, extra = divmod(global_batch, world)
    start = base + rank * per + min(rank, extra)
    return start, start + per + (1 if rank < extra else 0)


@dataclass
class LedgerEntry:
    shard: int       # rank that delivered the range
    step: int        # global step (epochs included)
    start: int       # epoch-local sample position, inclusive
    stop: int        # epoch-local sample position, exclusive
    attempt: int     # trainer attempt that delivered it
    epoch: int

    def to_list(self) -> list:
        return [self.shard, self.step, self.start, self.stop,
                self.attempt, self.epoch]

    @staticmethod
    def from_list(v) -> "LedgerEntry":
        return LedgerEntry(*[int(x) for x in v])


class SampleLedger:
    """Step-stamped record of delivered sample ranges.  Wire form (a
    typed Raw-envelope message, pinned in tests/test_schema.py)::

        {"t": "sample_ledger", "epoch": E,
         "entries": [[shard, step, start, stop, attempt, epoch], ...]}
    """

    def __init__(self, entries: Optional[list] = None):
        self.entries: list = list(entries or [])

    def record(self, shard: int, step: int, start: int, stop: int,
               attempt: int = 0, epoch: int = 0) -> LedgerEntry:
        e = LedgerEntry(shard, step, start, stop, attempt, epoch)
        self.entries.append(e)
        return e

    def merge(self, other: "SampleLedger") -> "SampleLedger":
        self.entries.extend(other.entries)
        return self

    def to_wire(self, epoch: int = 0) -> dict:
        return {"t": "sample_ledger", "epoch": int(epoch),
                "entries": [e.to_list() for e in self.entries]}

    @staticmethod
    def from_wire(m: dict) -> "SampleLedger":
        if m.get("t") == "sample_ledger":
            return SampleLedger([LedgerEntry.from_list(v)
                                 for v in m.get("entries", [])])
        raise ValueError(f"not a sample_ledger message: {m.get('t')!r}")

    def save(self, path: str) -> None:
        _atomic_write_json(path, self.to_wire())

    @staticmethod
    def load(path: str) -> "SampleLedger":
        with open(path) as f:
            return SampleLedger.from_wire(json.load(f))

    def max_step(self) -> int:
        return max((e.step for e in self.entries), default=-1)

    def __len__(self) -> int:
        return len(self.entries)


def merge_ledgers(ledger_dir: str,
                  save_to: Optional[str] = None) -> SampleLedger:
    """Fold every per-rank/attempt ledger file in ``ledger_dir`` into
    one SampleLedger (the driver-side view after any number of
    attempts and resizes)."""
    out = SampleLedger()
    for p in sorted(glob.glob(os.path.join(ledger_dir, "*.json"))):
        if os.path.basename(p).startswith("merged"):
            continue
        try:
            out.merge(SampleLedger.load(p))
        except Exception:
            continue   # a rank died mid-rewrite; its tmp never landed
    if save_to is not None:
        out.save(save_to)
    return out


def validate_ledger(ledger: SampleLedger, steps: int,
                    global_batch: int) -> dict:
    """Exactly-once proof over the trained prefix ``[0, steps)``.

    Checkpoint-consistency rule: for each step, the HIGHEST attempt
    that recorded deliveries is the surviving one — lower attempts'
    entries for that step were rolled back together with the step when
    the trainer restored an earlier checkpoint.  The surviving ranges
    must tile the step's global range exactly: any gap is a dropped
    sample, any overlap a double-feed."""
    spe_pos = {}   # step -> list of (start, stop) from surviving attempt
    by_step: dict = {}
    for e in ledger.entries:
        if 0 <= e.step < steps:
            by_step.setdefault(e.step, []).append(e)
    missing, double = [], []
    for s in range(steps):
        es = by_step.get(s, [])
        lo = s * global_batch
        hi = lo + global_batch
        if not es:
            missing.append([s, lo, hi])
            continue
        amax = max(e.attempt for e in es)
        ranges = sorted((e.start, e.stop) for e in es
                        if e.attempt == amax)
        spe_pos[s] = ranges
        pos = lo
        for (a, b) in ranges:
            if a < pos:
                double.append([s, a, min(b, pos)])
            elif a > pos:
                missing.append([s, pos, a])
            pos = max(pos, b)
        if pos < hi:
            missing.append([s, pos, hi])
        elif pos > hi:
            double.append([s, hi, pos])
    return {"ok": not missing and not double,
            "steps": steps, "global_batch": global_batch,
            "missing": missing, "double_fed": double}


@dataclass
class EpochManifest:
    """Row-offset index over a spooled epoch: ``row_offsets[i]`` is the
    epoch-local position of block i's first row (len = nblocks + 1)."""
    path: str
    block_files: list
    row_offsets: list
    total_rows: int
    columns: list = field(default_factory=list)
    epoch: int = 0

    def save(self) -> None:
        _atomic_write_json(self.path, {
            "t": "ingest_manifest", "epoch": self.epoch,
            "block_files": self.block_files,
            "row_offsets": self.row_offsets,
            "total_rows": self.total_rows, "columns": self.columns})

    @staticmethod
    def load(path: str) -> "EpochManifest":
        with open(path) as f:
            m = json.load(f)
        if m.get("t") == "ingest_manifest":
            return EpochManifest(path=path, block_files=m["block_files"],
                                 row_offsets=m["row_offsets"],
                                 total_rows=int(m["total_rows"]),
                                 columns=list(m.get("columns", [])),
                                 epoch=int(m.get("epoch", 0)))
        raise ValueError(f"not an ingest_manifest: {m.get('t')!r}")


def spool_epoch(ds, out_dir: str, *, epoch: int = 0,
                max_in_flight: int = 4,
                byte_budget: Optional[int] = None) -> EpochManifest:
    """Run the dataset's streaming plan and spool the output blocks
    (numeric columns, npz) plus a row-offset manifest under
    ``out_dir``.  Uses the operator-graph executor when the runtime is
    up (in-plan shuffles, byte budgets) and the seeded inline fallback
    otherwise — either way the spooled ROW ORDER is identical for a
    seeded plan."""
    import ray_tpu
    os.makedirs(out_dir, exist_ok=True)
    mode = "streaming" if ray_tpu.is_initialized() else "inline"
    files, offsets, columns = [], [0], []
    i = 0
    for blk in ds._iter_staged_blocks(mode, max_in_flight, byte_budget):
        cols = dict(B.to_columns(blk))
        n = int(B.num_rows(cols)) if cols else 0
        if n == 0:
            continue
        p = os.path.join(out_dir, f"block-{i:05d}.npz")
        np.savez(p, **{k: np.asarray(v) for k, v in cols.items()})
        files.append(os.path.basename(p))
        offsets.append(offsets[-1] + n)
        columns = sorted(cols)
        i += 1
    man = EpochManifest(path=os.path.join(out_dir, "manifest.json"),
                        block_files=files, row_offsets=offsets,
                        total_rows=offsets[-1], columns=columns,
                        epoch=epoch)
    man.save()
    return man


def ensure_spooled(ds, out_dir: str, **kw) -> EpochManifest:
    """Spool once per run: a pre-existing manifest wins (attempt
    restarts and readmissions must replay the SAME epoch order)."""
    path = os.path.join(out_dir, "manifest.json")
    if os.path.exists(path):
        return EpochManifest.load(path)
    return spool_epoch(ds, out_dir, **kw)


class DatasetShard:
    """Member-side view of a spooled epoch: yields this rank's
    contiguous sub-slice of each step's global batch and records every
    delivered range in the per-rank ledger file BEFORE handing the
    batch out (a died-mid-step delivery is superseded by the retry's
    higher attempt under the validate_ledger rule).

    Reading is positional over the manifest's row offsets, so a
    (rank, world) re-shard is O(1) — no data movement, the next
    ``iter_batches(start_step=...)`` simply computes different slices.
    """

    def __init__(self, manifest_path: str, *, rank: int, world: int,
                 global_batch: int, ledger_dir: str, attempt: int = 0,
                 epochs: int = 1, name: str = "train"):
        self.manifest = EpochManifest.load(manifest_path)
        self.rank = int(rank)
        self.world = int(world)
        self.global_batch = int(global_batch)
        self.epochs = max(1, int(epochs))
        self.attempt = int(attempt)
        self.name = name
        self.ledger = SampleLedger()
        self._dir = os.path.dirname(self.manifest.path)
        self._ledger_path = os.path.join(
            ledger_dir, f"{name}-rank{rank}-attempt{attempt}.json")
        os.makedirs(ledger_dir, exist_ok=True)
        self._cache: dict = {}   # block idx -> column dict (tiny LRU)

    # -- geometry

    @property
    def steps_per_epoch(self) -> int:
        """Full global batches per epoch (the ragged tail is dropped,
        like drop_last — a partial step would change shape under
        resize)."""
        return self.manifest.total_rows // self.global_batch

    @property
    def total_steps(self) -> int:
        return self.steps_per_epoch * self.epochs

    def _chaos(self, point: str, **ctx) -> None:
        """Chaos-plane trigger (hotpath_registry contract: disarmed =
        one global load + is-None branch)."""
        fi = _fi._active
        if fi is None:
            return
        ctx["shard"] = self.name
        fi.on_data(point, ctx)

    # -- positional reads

    def _block_cols(self, bi: int) -> dict:
        cols = self._cache.get(bi)
        if cols is None:
            p = os.path.join(self._dir, self.manifest.block_files[bi])
            with np.load(p, allow_pickle=False) as z:
                cols = {k: z[k] for k in z.files}
            if len(self._cache) >= 2:   # ranges advance sequentially
                self._cache.pop(next(iter(self._cache)))
            self._cache[bi] = cols
        return cols

    def read_rows(self, start: int, stop: int) -> dict:
        """Rows [start, stop) of the spooled epoch order as a column
        dict (crosses block boundaries as needed)."""
        offs = self.manifest.row_offsets
        parts = []
        pos = start
        while pos < stop:
            bi = bisect.bisect_right(offs, pos) - 1
            lo, hi = offs[bi], offs[bi + 1]
            take = min(stop, hi) - pos
            cols = self._block_cols(bi)
            parts.append({k: v[pos - lo:pos - lo + take]
                          for k, v in cols.items()})
            pos += take
        if not parts:
            return {k: np.empty(0) for k in self.manifest.columns}
        if len(parts) == 1:
            return parts[0]
        return {k: np.concatenate([p[k] for p in parts])
                for k in parts[0]}

    # -- the training feed

    def iter_batches(self, start_step: int = 0) -> Iterator[tuple]:
        """Yield ``(global_step, batch)`` from ``start_step`` (the
        restored checkpoint's next step) to the end of the last epoch.
        Every yield is ledger-recorded and flushed first."""
        spe = self.steps_per_epoch
        for s in range(int(start_step), self.total_steps):
            ep, es = divmod(s, spe)
            self._chaos("data_dispatch", rank=self.rank, step=s,
                        epoch=ep)
            g0, g1 = shard_range(es, self.global_batch, self.rank,
                                 self.world)
            self.ledger.record(self.rank, s, g0, g1,
                               attempt=self.attempt, epoch=ep)
            self.ledger.save(self._ledger_path)
            yield s, self.read_rows(g0, g1)
