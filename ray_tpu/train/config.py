"""Typed run/scaling configs (reference capability: python/ray/air/config.py
ScalingConfig/RunConfig/FailureConfig/CheckpointConfig — same roles,
TPU-topology-aware fields instead of num_gpus floats)."""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class ScalingConfig:
    """How to scale training.

    Where the reference exposes ``num_workers``×``use_gpu``
    (air/config.py ScalingConfig), parallelism here is a *mesh spec*:
    named axis sizes laid over the gang's devices (dp/fsdp/tp/sp/ep/pp;
    -1 = fill).  ``num_hosts`` scales over TPU hosts (one gang member per
    host, jax.distributed); within a host all chips are always used —
    that is the SPMD unit, not a tunable.
    """
    mesh: dict[str, int] = field(default_factory=lambda: {"dp": -1})
    num_hosts: int = 1
    use_cpu_devices: bool = False       # tests: virtual CPU device mesh
    # multi-host CPU test shape: virtual devices per member process
    # (0 = all local devices; real TPU hosts always use all chips)
    devices_per_host: int = 0
    # extra custom resources each gang member reserves (placement)
    resources_per_host: Optional[dict] = None
    num_tpus_per_host: float = 0
    # Elastic gang recovery: on member death, shrink to the survivors
    # (same processes, dp resharded) and resume from checkpoint instead
    # of tearing the whole gang down; replacements are re-admitted at
    # the next re-gang boundary.  Full restart remains the fallback
    # whenever fewer than ``min_hosts`` members survive or the reform
    # itself fails.
    elastic: bool = True
    min_hosts: int = 1
    # reference-compat aliases: ScalingConfig(num_workers=8) on a CPU mesh
    num_workers: Optional[int] = None

    def __post_init__(self):
        if self.num_workers is not None and self.mesh == {"dp": -1}:
            self.mesh = {"dp": self.num_workers}


@dataclass
class FailureConfig:
    """Restart-based FT (reference: air/config.py FailureConfig;
    restart semantics per train/_internal/backend_executor.py:571 —
    on TPU a member loss breaks the ICI mesh, so recovery is always
    rebuild-gang + restore-from-checkpoint)."""
    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """(reference: air/config.py CheckpointConfig)"""
    num_to_keep: Optional[int] = None
    checkpoint_frequency: int = 0        # steps between checkpoints; 0 = off
    checkpoint_at_end: bool = True
    async_write: bool = True


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None   # local dir or mounted FS
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    callbacks: list = field(default_factory=list)   # tune.Callback hooks
    # stop criteria: {"metric": threshold} — a trial stops when any
    # reported metric reaches its threshold (reference: tune.run(stop=...))
    stop: Optional[dict] = None

    def resolved_storage_path(self) -> str:
        base = self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results")
        return os.path.join(base, self.name or "run")
