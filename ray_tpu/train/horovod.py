"""Horovod distributed-training backend.

Reference capability: train/horovod/config.py:32 HorovodConfig — the
backend assembles Horovod's rendezvous environment on every worker
(rank/size/local-rank layout + the gloo rendezvous server address on
rank 0) and the user loop's ``hvd.init()`` forms the ring.  horovod
itself is imported only by the USER loop; the backend's env contract is
testable without it.
"""

from __future__ import annotations

import socket
from collections import defaultdict
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import BaseTrainer


@dataclass
class HorovodConfig:
    """(reference: horovod/config.py:32)"""
    init_timeout_s: float = 120.0


def build_horovod_env(hosts: list, rank: int,
                      rendezvous_addr: str,
                      rendezvous_port: int) -> dict:
    """Per-rank Horovod env (reference: horovod/config.py + the
    horovod.ray coordinator): global rank/size, per-host local
    rank/size, cross-host rank/size, gloo rendezvous location."""
    by_host: dict = defaultdict(list)
    for r, h in enumerate(hosts):
        by_host[h].append(r)
    host = hosts[rank]
    local_ranks = by_host[host]
    host_order = list(dict.fromkeys(hosts))
    return {
        "HOROVOD_RANK": str(rank),
        "HOROVOD_SIZE": str(len(hosts)),
        "HOROVOD_LOCAL_RANK": str(local_ranks.index(rank)),
        "HOROVOD_LOCAL_SIZE": str(len(local_ranks)),
        "HOROVOD_CROSS_RANK": str(host_order.index(host)),
        "HOROVOD_CROSS_SIZE": str(len(host_order)),
        "HOROVOD_CONTROLLER": "gloo",
        "HOROVOD_CPU_OPERATIONS": "gloo",
        "HOROVOD_GLOO_RENDEZVOUS_ADDR": rendezvous_addr,
        "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rendezvous_port),
        "HOROVOD_HOSTNAME": host,
    }


class _HorovodWorker:
    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._ckpt_payload = None

    def hostname(self) -> str:
        return socket.gethostbyname(socket.gethostname())

    def probe_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def setup(self, hosts: list, rendezvous_addr: str,
              rendezvous_port: int) -> dict:
        import os
        env = build_horovod_env(hosts, self.rank, rendezvous_addr,
                                rendezvous_port)
        os.environ.update(env)
        return env

    def run(self, loop: Callable, config: dict, restore_payload) -> dict:
        from ray_tpu.train import session as _s
        worker = self

        def ckpt_cb(data):
            worker._ckpt_payload = data
            return None

        latest = (Checkpoint.from_dict(restore_payload)
                  if restore_payload is not None else None)
        st = _s._start(world_rank=self.rank, world_size=self.world_size,
                       checkpoint_cb=ckpt_cb, latest_checkpoint=latest)
        try:
            if loop.__code__.co_argcount == 0:
                loop()
            else:
                loop(dict(config))
        except StopIteration:
            pass
        finally:
            _s._end()
        reports = [{k: v for k, v in r.items()
                    if k != "_checkpoint_path"} for r in st.results]
        return {"reports": reports,
                "checkpoint": self._ckpt_payload if self.rank == 0
                else None}


class HorovodTrainer(BaseTrainer):
    """(reference: train/horovod/horovod_trainer.py)"""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 horovod_config: Optional[HorovodConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._hvd_config = horovod_config or HorovodConfig()

    @property
    def _num_workers(self) -> int:
        sc = self.scaling_config
        if sc.num_workers is not None:
            return sc.num_workers
        dp = sc.mesh.get("dp", 1)
        return dp if dp > 0 else 1

    def _attempt(self) -> None:
        import ray_tpu
        from ray_tpu.train import session as _session
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        hc = self._hvd_config
        world = self._num_workers
        Worker = ray_tpu.remote(_HorovodWorker)
        workers = [Worker.remote(r, world) for r in range(world)]
        st = _session._state()
        st.world_size = world
        restore = st.latest_checkpoint
        restore_payload = restore.to_dict() if restore is not None else None
        try:
            hosts = ray_tpu.get([w.hostname.remote() for w in workers],
                                timeout=hc.init_timeout_s)
            port = ray_tpu.get(workers[0].probe_port.remote(),
                               timeout=hc.init_timeout_s)
            ray_tpu.get([w.setup.remote(hosts, hosts[0], port)
                         for w in workers], timeout=hc.init_timeout_s)
            outs = ray_tpu.get(
                [w.run.remote(self._loop, self._loop_config,
                              restore_payload) for w in workers],
                timeout=None)
            rank0 = outs[0]
            n = len(rank0["reports"])
            for i, metrics in enumerate(rank0["reports"]):
                ck = rank0["checkpoint"] if i == n - 1 else None
                _session.report(metrics, checkpoint=ck)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
