"""TensorFlow distributed-training backend.

Reference capability: train/tensorflow/config.py:21 TensorflowConfig —
the backend's ONLY job is assembling TF_CONFIG on every worker so the
user loop's ``tf.distribute.MultiWorkerMirroredStrategy()`` forms the
collective ring; Ray stays out of the gradient path.  Same split here:
a worker gang probes reachable host:port pairs, the driver assembles
the cluster spec, each rank gets TF_CONFIG before the user loop runs.
TensorFlow itself is imported only by the USER loop — this backend is
import-gated exactly where the reference is (tf absent = the loop's
import fails with the obvious message, the backend still works).
"""

from __future__ import annotations

import json
import os
import socket
from dataclasses import dataclass
from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.trainer import BaseTrainer


@dataclass
class TensorflowConfig:
    """(reference: tensorflow/config.py:21)"""
    init_timeout_s: float = 120.0


def build_tf_config(worker_addrs: list, rank: int) -> str:
    """The TF_CONFIG JSON for MultiWorkerMirroredStrategy (reference:
    tensorflow/config.py _setup_tensorflow_environment)."""
    return json.dumps({
        "cluster": {"worker": list(worker_addrs)},
        "task": {"type": "worker", "index": rank},
    })


class _TFWorker:
    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self._ckpt_payload = None

    def probe_address(self) -> str:
        host = socket.gethostbyname(socket.gethostname())
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return f"{host}:{port}"

    def setup(self, worker_addrs: list) -> bool:
        os.environ["TF_CONFIG"] = build_tf_config(worker_addrs,
                                                  self.rank)
        return True

    def run(self, loop: Callable, config: dict, restore_payload) -> dict:
        from ray_tpu.train import session as _s
        worker = self

        def ckpt_cb(data):
            worker._ckpt_payload = data
            return None

        latest = (Checkpoint.from_dict(restore_payload)
                  if restore_payload is not None else None)
        st = _s._start(world_rank=self.rank, world_size=self.world_size,
                       checkpoint_cb=ckpt_cb, latest_checkpoint=latest)
        try:
            if loop.__code__.co_argcount == 0:
                loop()
            else:
                loop(dict(config))
        except StopIteration:
            pass
        finally:
            _s._end()
        reports = [{k: v for k, v in r.items()
                    if k != "_checkpoint_path"} for r in st.results]
        return {"reports": reports,
                "checkpoint": self._ckpt_payload if self.rank == 0
                else None}


class TensorflowTrainer(BaseTrainer):
    """(reference: train/tensorflow/tensorflow_trainer.py)"""

    def __init__(self, train_loop_per_worker: Callable, *,
                 train_loop_config: Optional[dict] = None,
                 tensorflow_config: Optional[TensorflowConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config,
                         resume_from_checkpoint=resume_from_checkpoint)
        self._loop = train_loop_per_worker
        self._loop_config = train_loop_config or {}
        self._tf_config = tensorflow_config or TensorflowConfig()

    @property
    def _num_workers(self) -> int:
        sc = self.scaling_config
        if sc.num_workers is not None:
            return sc.num_workers
        dp = sc.mesh.get("dp", 1)
        return dp if dp > 0 else 1

    def _attempt(self) -> None:
        import ray_tpu
        from ray_tpu.train import session as _session
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        tc = self._tf_config
        world = self._num_workers
        Worker = ray_tpu.remote(_TFWorker)
        workers = [Worker.remote(r, world) for r in range(world)]
        st = _session._state()
        st.world_size = world
        restore = st.latest_checkpoint
        restore_payload = restore.to_dict() if restore is not None else None
        try:
            addrs = ray_tpu.get(
                [w.probe_address.remote() for w in workers],
                timeout=tc.init_timeout_s)
            ray_tpu.get([w.setup.remote(addrs) for w in workers],
                        timeout=tc.init_timeout_s)
            outs = ray_tpu.get(
                [w.run.remote(self._loop, self._loop_config,
                              restore_payload) for w in workers],
                timeout=None)
            rank0 = outs[0]
            n = len(rank0["reports"])
            for i, metrics in enumerate(rank0["reports"]):
                ck = rank0["checkpoint"] if i == n - 1 else None
                _session.report(metrics, checkpoint=ck)
        finally:
            for w in workers:
                try:
                    ray_tpu.kill(w)
                except Exception:
                    pass
