"""JaxTrainer: declarative model+optimizer training (the framework-native
trainer — the reference's closest analogues are its framework trainers,
e.g. TorchTrainer wrapping DDP setup; here the "backend" is a sharded
compiled train step from train.step).

Give it a loss_fn, param init, optax optimizer, a batch iterator and a
mesh spec; it builds the sharded step, runs it, reports metrics, and
checkpoints periodically.  TP/PP/SP/FSDP are *config*, not code: they are
just different mesh axes + sharding rules on the same loss_fn.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Iterable, Optional

import jax
import jax.numpy as jnp
import optax

from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules
from ray_tpu.train import session
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.step import TrainState, make_train_step, shard_batch
from ray_tpu.train.trainer import DataParallelTrainer


class JaxTrainer(DataParallelTrainer):
    def __init__(self, *, loss_fn: Callable,
                 init_params: Callable[[jax.Array], Any],
                 optimizer: optax.GradientTransformation,
                 train_data: Iterable,
                 num_steps: int,
                 params_logical: Any = None,
                 rules: Rules = DEFAULT_LLM_RULES,
                 eval_fn: Optional[Callable] = None,
                 eval_every: int = 0,
                 report_every: int = 10,
                 checkpoint_every: int = 0,
                 seed: int = 0,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 **kw):
        self._opts = dict(
            loss_fn=loss_fn, init_params=init_params, optimizer=optimizer,
            train_data=train_data, num_steps=num_steps,
            params_logical=params_logical, rules=rules, eval_fn=eval_fn,
            eval_every=eval_every, report_every=report_every,
            checkpoint_every=checkpoint_every, seed=seed)
        super().__init__(self._train_loop, scaling_config=scaling_config,
                         run_config=run_config, **kw)

    def _train_loop(self, _cfg):
        o = self._opts
        mesh = self.gang.mesh
        loss_fn = o["loss_fn"]
        # model loss_fns that take mesh/rules get them bound here
        try:
            import inspect
            sig = inspect.signature(loss_fn)
            if "mesh" in sig.parameters:
                import functools
                loss_fn = functools.partial(loss_fn, mesh=mesh,
                                            rules=o["rules"])
        except (ValueError, TypeError):
            pass

        init_fn, step_fn = make_train_step(
            loss_fn, o["optimizer"], mesh=mesh,
            params_logical=o["params_logical"], rules=o["rules"])

        restored = session.get_checkpoint()
        params = o["init_params"](jax.random.PRNGKey(o["seed"]))
        state = init_fn(params)
        start_step = 0
        if restored is not None:
            payload = restored.to_dict()
            start_step = int(payload.get("step", 0))

            def put_like(cur, host):
                if isinstance(cur, jax.Array):
                    return jax.device_put(host, cur.sharding)
                return host

            # full-state restore: params AND optimizer moments AND step —
            # re-initializing the optimizer would spike the effective LR
            # after every failover (adam bias correction restarts)
            state = TrainState(
                step=put_like(state.step,
                              jnp.asarray(start_step, jnp.int32)),
                params=jax.tree.map(put_like, state.params,
                                    payload["params"]),
                opt_state=(jax.tree.map(put_like, state.opt_state,
                                        payload["opt_state"])
                           if "opt_state" in payload else state.opt_state))

        data_iter = iter(o["train_data"])
        # replay the iterator to the resume point so deterministic feeds
        # don't re-consume the leading batches
        for _ in range(start_step):
            next(data_iter)
        t0 = time.perf_counter()
        tokens_done = 0
        for i in range(start_step, o["num_steps"]):
            batch = next(data_iter)
            batch = shard_batch(batch, mesh)
            state, metrics = step_fn(state, batch)
            leaf = jax.tree.leaves(batch)[0]
            tokens_done += int(leaf.shape[0]) * (
                int(leaf.shape[1]) if leaf.ndim > 1 else 1)

            is_last = i + 1 == o["num_steps"]
            if (i + 1) % o["report_every"] == 0 or is_last:
                m = {k: float(v) for k, v in metrics.items()}
                dt = time.perf_counter() - t0
                m.update(step=i + 1, throughput=tokens_done / max(dt, 1e-9))
                if (o["eval_fn"] is not None and o["eval_every"]
                        and (i + 1) % o["eval_every"] == 0):
                    m["eval"] = float(o["eval_fn"](state.params))
                ckpt = None
                if (o["checkpoint_every"]
                        and (i + 1) % o["checkpoint_every"] == 0) or is_last:
                    ckpt = {"params": state.params,
                            "opt_state": state.opt_state,
                            "step": i + 1}
                session.report(m, checkpoint=ckpt)
        self.final_state = state
