"""TransformersTrainer: Hugging Face Trainer loops on the cluster.

Reference capability: python/ray/train/huggingface/ —
TransformersTrainer (huggingface_trainer.py): each worker constructs a
``transformers.Trainer`` via ``trainer_init_per_worker`` and runs it
under torch.distributed so HF's own DDP integration shards the batch;
HF log events flow back as session reports.

ray_tpu shape: a thin specialization of TorchTrainer — the worker loop
builds the HF trainer inside the initialized gloo process group
(transformers reads RANK/WORLD_SIZE/MASTER_* from the env our
_TorchWorker.setup_pg exports), bridges ``on_log`` to
``session.report``, and ships rank-0's final model state dict as the
checkpoint payload.
"""

from __future__ import annotations

from typing import Callable, Optional

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.torch_trainer import TorchConfig, TorchTrainer


def _make_loop(trainer_init_per_worker: Callable):
    def loop(config):
        import transformers

        from ray_tpu.train import session

        hf_trainer = trainer_init_per_worker(config)
        if not isinstance(hf_trainer, transformers.Trainer):
            raise TypeError(
                "trainer_init_per_worker must return a "
                f"transformers.Trainer, got {type(hf_trainer).__name__}")

        class _ReportCallback(transformers.TrainerCallback):
            """HF log events → session.report (reference:
            huggingface/_huggingface_utils.py TrainReportCallback)."""

            def on_log(self, args, state, control, logs=None, **kw):
                if logs:
                    session.report(
                        {k: v for k, v in logs.items()
                         if isinstance(v, (int, float))})

        hf_trainer.add_callback(_ReportCallback())

        # restore: the trainer's resume checkpoint (or a restart-FT
        # retry's last good state) carries rank-0's state_dict — load it
        # before training so resume actually resumes
        ck = session.get_checkpoint()
        if ck is not None:
            import torch
            payload = ck.to_dict()
            sd = payload.get("state_dict")
            if sd:
                model = getattr(hf_trainer.model, "module",
                                hf_trainer.model)
                model.load_state_dict(
                    {k: torch.as_tensor(v) for k, v in sd.items()})

        result = hf_trainer.train()

        final = {"training_loss": float(result.training_loss),
                 "global_step": int(result.global_step)}
        ckpt = None
        if session.get_world_rank() == 0:
            import numpy as np
            model = hf_trainer.model
            # unwrap DDP if HF wrapped it
            model = getattr(model, "module", model)
            ckpt = {"state_dict": {
                k: np.asarray(v.detach().cpu())
                for k, v in model.state_dict().items()},
                **final}
        session.report(final, checkpoint=ckpt)

    return loop


class TransformersTrainer(TorchTrainer):
    """(reference: train/huggingface/huggingface_trainer.py
    TransformersTrainer / HuggingFaceTrainer)"""

    def __init__(self, trainer_init_per_worker: Callable, *,
                 trainer_init_config: Optional[dict] = None,
                 torch_config: Optional[TorchConfig] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resume_from_checkpoint: Optional[Checkpoint] = None):
        super().__init__(
            _make_loop(trainer_init_per_worker),
            train_loop_config=trainer_init_config or {},
            torch_config=torch_config,
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint)
