"""Per-worker training session: ``report(metrics, checkpoint=...)``.

Reference capability: ray.air.session (python/ray/air/session.py:41
session.report) + the per-worker _TrainSession thread/queue handoff
(train/_internal/session.py:63,325).  Here the single-host fast path has
no thread hop: the training loop runs in the driver (or gang member)
process and report() appends to an in-process buffer the trainer drains;
multi-host members report through the object store.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.train.checkpoint import Checkpoint


@dataclass
class _SessionState:
    world_rank: int = 0
    world_size: int = 1
    results: list = field(default_factory=list)
    latest_checkpoint: Optional[Checkpoint] = None
    checkpoint_cb: Any = None     # callable(dict) -> path, set by trainer
    stop_requested: bool = False
    dataset_shards: dict = field(default_factory=dict)  # name -> DatasetShard


_local = threading.local()


def _state() -> _SessionState:
    st = getattr(_local, "session", None)
    if st is None:
        raise RuntimeError(
            "No active train session — session.* calls are only valid "
            "inside a train_loop_per_worker launched by a Trainer.")
    return st


def _start(world_rank=0, world_size=1, checkpoint_cb=None,
           latest_checkpoint=None) -> _SessionState:
    st = _SessionState(world_rank=world_rank, world_size=world_size,
                       checkpoint_cb=checkpoint_cb,
                       latest_checkpoint=latest_checkpoint)
    _local.session = st
    return st


def _end():
    _local.session = None


def report(metrics: dict, *, checkpoint: Optional[dict] = None) -> None:
    """Report metrics (and optionally a checkpoint payload) for this step
    (reference: air/session.py:41)."""
    st = _state()
    entry = dict(metrics)
    if checkpoint is not None and st.checkpoint_cb is not None:
        path = st.checkpoint_cb(checkpoint)
        entry["_checkpoint_path"] = path
    st.results.append(entry)
    if st.stop_requested:
        raise StopIteration("session stop requested")


def get_checkpoint() -> Optional[Checkpoint]:
    """Checkpoint to resume from, if the trainer restored one
    (reference: session.get_checkpoint)."""
    return _state().latest_checkpoint


def get_dataset_shard(name: str = "train"):
    """This rank's streamed-ingest shard (a ``train.ingest.DatasetShard``)
    when the trainer was given ``datasets=`` on a multi-host gang
    (reference: session.get_dataset_shard).  ``shard.iter_batches(
    start_step=...)`` yields ``(step, batch)`` with exactly-once ledger
    accounting; after an elastic resize the SAME call re-shards
    automatically because data position is a pure function of
    (step, rank, world) — see train/ingest.py."""
    return _state().dataset_shards.get(name)


def get_world_rank() -> int:
    return _state().world_rank


def get_world_size() -> int:
    return _state().world_size
