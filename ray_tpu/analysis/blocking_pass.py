"""Pass 2: event-loop blocking calls.

Every control-plane service is ONE thread (``core/service.py``): a
selector loop that runs ``_h_*`` handlers, the periodic ``on_tick``,
posted callbacks, and timers inline.  A single blocking call anywhere
under a handler stalls task dispatch, heartbeats (getting a healthy
node declared dead), and object transfers for the whole node — which is
why worker-process reaping moved off ``waitpid`` scans and peer/head
dials run on dedicated threads.

This pass builds a conservative call graph over ``ray_tpu/core/`` and
walks it from the event-loop entry points, reporting any reachable
blocking primitive with the call chain that reaches it:

  * ``time.sleep``                       (incl. transitively, e.g.
                                          ``fault_injection.apply_delay``,
                                          and bare ``from time import
                                          sleep`` imports)
  * ``subprocess.run/call/check_call/check_output``
  * ``os.waitpid`` without ``WNOHANG``
  * ``socket.create_connection``; ``sendall`` by attribute name (always
    blocking on a blocking socket — per-receiver mode is out of static
    reach); argless ``.wait()`` / ``.communicate()`` (indefinite block:
    a timeout argument bounds them and is accepted).

Call-graph edges (deliberately conservative — unresolved calls are
dropped, and the tier-1 fixture tests pin the shapes that must keep
resolving):

  * bare names → same-module functions / from-imports of core modules
  * ``mod.func(...)`` through a module alias (``_fi.apply_delay``)
  * ``self.meth(...)`` through the class and its bases (NodeService →
    EventLoopService/ClusterStoreMixin)
  * ``<alias>._active.meth(...)`` → methods of the classes in that
    module (the fault-injection / flight-recorder hook surface)
  * ``obj.meth(...)`` when exactly one scanned class defines ``meth``
    (unique-name dispatch; ambiguous names are skipped, not guessed)

Nested ``def``s are attributed to their enclosing function: a closure
built in a handler and posted back to the loop still runs on the loop.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.analysis.common import (Finding, FunctionIndexer,
                                     import_aliases, iter_py_files,
                                     parse_file, rel, repo_root)

DEFAULT_SUBDIRS = ["ray_tpu/core"]

# loop-thread entry points: message handlers, head/peer push dispatch,
# the periodic tick, and the dispatcher itself
ROOT_NAMES = {"on_tick", "_dispatch", "_on_head_msg", "_on_peer_msg",
              "_run_due_timers"}

_BLOCKING_MODULE_CALLS = {
    ("time", "sleep"): "time.sleep",
    ("subprocess", "run"): "subprocess.run",
    ("subprocess", "call"): "subprocess.call",
    ("subprocess", "check_call"): "subprocess.check_call",
    ("subprocess", "check_output"): "subprocess.check_output",
    ("socket", "create_connection"): "socket.create_connection",
}

# attribute names that block regardless of receiver type
_BLOCKING_ATTRS = {"sendall"}

# attribute calls that block INDEFINITELY when called with no arguments
# (Popen.wait(), Event.wait(), Popen.communicate()); a timeout argument
# bounds them, so only the bare form is flagged
_BLOCKING_IF_ARGLESS = {"wait", "communicate"}


@dataclass
class _Fn:
    info: object
    calls: list = field(default_factory=list)       # resolved (kind, key)
    primitives: list = field(default_factory=list)  # (name, line)


def _thread_target_names(func_node) -> set:
    """Names of nested defs handed to ``threading.Thread(target=...)``
    (or a pool's ``submit``): those bodies run on their OWN thread, not
    the event loop, so the enclosing-function attribution must skip
    them.  Other closures (posted callbacks, RPC continuations) stay
    attributed to the enclosing function — they do run on the loop."""
    out = set()
    for n in ast.walk(func_node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name == "Thread":
            for kw in n.keywords:
                if kw.arg == "target" and isinstance(kw.value, ast.Name):
                    out.add(kw.value.id)
        elif name == "submit" and n.args \
                and isinstance(n.args[0], ast.Name):
            out.add(n.args[0].id)
    return out


class _BodyScan(ast.NodeVisitor):
    """Collect call edges + blocking primitives from one function body."""

    def __init__(self, fn: _Fn, aliases: dict, module_key: str):
        self.fn = fn
        self.aliases = aliases
        self.module_key = module_key
        self._root = None
        self._skip_defs: set = set()

    def _visit_func(self, node) -> None:
        if self._root is None:
            self._root = node
            self._skip_defs = _thread_target_names(node)
            self.generic_visit(node)
        elif node.name not in self._skip_defs:
            self.generic_visit(node)
        # else: a Thread-target closure — runs off-loop, skip its body

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Name):
            target = self.aliases.get(f.id, f.id)
            if "." in target:
                parts = target.split(".")
                # bare-name from-import of a blocking primitive:
                # `from time import sleep; sleep(1)`
                if (parts[0], parts[-1]) in _BLOCKING_MODULE_CALLS:
                    self.fn.primitives.append(
                        (_BLOCKING_MODULE_CALLS[(parts[0], parts[-1])],
                         node.lineno))
                elif parts[0] == "os" and parts[-1] == "waitpid":
                    self._check_waitpid(node)
                elif len(parts) >= 3 \
                        and ".".join(parts[:-2]).endswith("ray_tpu.core"):
                    # from-import of a core function:
                    # "ray_tpu.core.fault_injection.apply_delay"
                    self.fn.calls.append(("modfunc",
                                          (parts[-2], parts[-1])))
            else:
                self.fn.calls.append(("local",
                                      (self.module_key, f.id)))
        elif isinstance(f, ast.Attribute):
            self._attr_call(f, node)
        self.generic_visit(node)

    def _check_waitpid(self, node: ast.Call) -> None:
        if not any("WNOHANG" in ast.dump(a) for a in node.args[1:]):
            self.fn.primitives.append(
                ("os.waitpid (no WNOHANG)", node.lineno))

    def _attr_call(self, f: ast.Attribute, node: ast.Call) -> None:
        attr = f.attr
        recv = f.value
        if isinstance(recv, ast.Name):
            target_mod = self.aliases.get(recv.id)
            if target_mod is not None:
                top = target_mod.split(".")[0]
                leaf = target_mod.split(".")[-1]
                if (top, attr) in _BLOCKING_MODULE_CALLS:
                    self.fn.primitives.append(
                        (_BLOCKING_MODULE_CALLS[(top, attr)], node.lineno))
                    return
                if top == "os" and attr == "waitpid":
                    self._check_waitpid(node)
                    return
                if target_mod.startswith("ray_tpu."):
                    self.fn.calls.append(("modfunc", (leaf, attr)))
                # any other module alias: a non-blocking stdlib call —
                # never unique-name dispatch (os.kill must not resolve
                # to a scanned class's .kill method)
                return
            if recv.id == "self":
                self.fn.calls.append(("self", attr))
                return
        # <alias>._active.meth(...) — the chaos/recorder hook surface
        if isinstance(recv, ast.Attribute) and recv.attr == "_active" \
                and isinstance(recv.value, ast.Name):
            target_mod = self.aliases.get(recv.value.id, "")
            if target_mod.startswith("ray_tpu."):
                self.fn.calls.append(
                    ("modmethod", (target_mod.split(".")[-1], attr)))
                return
        if attr in _BLOCKING_ATTRS:
            self.fn.primitives.append((attr, node.lineno))
            return
        if attr in _BLOCKING_IF_ARGLESS and not node.args \
                and not node.keywords:
            self.fn.primitives.append(
                (f".{attr}() with no timeout", node.lineno))
            return
        # fall through: unique-name dispatch resolved later
        self.fn.calls.append(("unique", attr))


@dataclass
class CallGraph:
    fns: dict = field(default_factory=dict)        # qual key -> _Fn
    by_module: dict = field(default_factory=dict)  # mod -> {qual: _Fn}
    classes: dict = field(default_factory=dict)    # class -> (mod, bases)
    methods: dict = field(default_factory=dict)    # class -> {name: key}
    method_name_index: dict = field(default_factory=dict)  # name -> [keys]

    def key(self, module_key: str, qualname: str) -> str:
        return f"{module_key}:{qualname}"

    def resolve_self(self, cls: str, meth: str,
                     _downward: bool = True) -> Optional[str]:
        seen = set()
        queue = deque([cls])
        while queue:
            c = queue.popleft()
            if c in seen:
                continue
            seen.add(c)
            key = self.methods.get(c, {}).get(meth)
            if key is not None:
                return key
            _, bases = self.classes.get(c, ("", []))
            queue.extend(bases)
        if not _downward:
            return None
        # Downward fallback — mixin composition: a stateless mixin's
        # method calls a SIBLING mixin's method through self, and the
        # definition lives in another base of the composed class (the
        # node split: NodeSchedMixin._schedule -> self._maybe_spawn_
        # worker in NodeWorkersMixin, composed by NodeService).  Resolve
        # through classes that inherit `cls`, one level of composition,
        # and only when every composition agrees on ONE definition —
        # ambiguity is dropped, not guessed, like unique-name dispatch.
        found = set()
        for sub, (_, bases) in self.classes.items():
            if cls in bases:
                key = self.resolve_self(sub, meth, _downward=False)
                if key is not None:
                    found.add(key)
        if len(found) == 1:
            return found.pop()
        return None

    def edges(self, key: str) -> list:
        fn = self.fns.get(key)
        if fn is None:
            return []
        out = []
        cls = fn.info.class_name
        for kind, ref in fn.calls:
            if kind == "local":
                mod, name = ref
                k = self.key(mod, name)
                if k in self.fns:
                    out.append(k)
            elif kind == "modfunc":
                mod, name = ref
                k = self.key(mod, name)
                if k in self.fns:
                    out.append(k)
            elif kind == "self" and cls:
                k = self.resolve_self(cls, ref)
                if k:
                    out.append(k)
            elif kind == "modmethod":
                mod, name = ref
                for k in self.method_name_index.get(name, []):
                    if k.startswith(mod + ":"):
                        out.append(k)
            elif kind == "unique":
                keys = self.method_name_index.get(ref, [])
                if len(keys) == 1:
                    out.append(keys[0])
        return out


def build_graph(root: Optional[str] = None,
                subdirs: Optional[list] = None) -> CallGraph:
    root = root or repo_root()
    graph = CallGraph()
    for path in iter_py_files(root, subdirs or DEFAULT_SUBDIRS):
        tree = parse_file(path)
        if tree is None:
            continue
        relfile = rel(path, root)
        module_key = relfile.rsplit("/", 1)[-1][:-3]
        idx = FunctionIndexer(relfile, module_key)
        idx.visit(tree)
        aliases = import_aliases(tree)
        for cls, bases in idx.classes.items():
            graph.classes[cls] = (module_key, bases)
        for qual, info in idx.functions.items():
            fn = _Fn(info=info)
            _BodyScan(fn, aliases, module_key).visit(info.node)
            key = graph.key(module_key, qual)
            graph.fns[key] = fn
            graph.by_module.setdefault(module_key, {})[qual] = fn
            if info.class_name:
                graph.methods.setdefault(info.class_name, {})[
                    info.name] = key
                graph.method_name_index.setdefault(info.name, []).append(
                    key)
    return graph


def roots_of(graph: CallGraph) -> list:
    out = []
    for key, fn in graph.fns.items():
        name = fn.info.name
        if name.startswith("_h_") or name.startswith("_hh_") \
                or name in ROOT_NAMES:
            out.append(key)
    return sorted(out)


def run(root: Optional[str] = None,
        subdirs: Optional[list] = None,
        max_depth: int = 12) -> list:
    graph = build_graph(root, subdirs)
    # BFS from all roots at once; first (shortest) path to a function wins
    parent: dict[str, Optional[str]] = {}
    depth: dict[str, int] = {}
    queue: deque = deque()
    for r in roots_of(graph):
        parent[r] = None
        depth[r] = 0
        queue.append(r)
    while queue:
        key = queue.popleft()
        if depth[key] >= max_depth:
            continue
        for nxt in graph.edges(key):
            if nxt not in parent:
                parent[nxt] = key
                depth[nxt] = depth[key] + 1
                queue.append(nxt)

    # the ident is line-free (stable for baselining), so multiple
    # occurrences of one primitive in one function share a finding —
    # every line is listed, or fixing the first would just reveal the
    # next on a later run
    grouped: dict = {}
    for key in parent:
        fn = graph.fns[key]
        for prim, line in fn.primitives:
            ident = (f"blocking:{fn.info.file}:{fn.info.qualname}"
                     f":{prim.split(' ')[0]}")
            if ident not in grouped:
                chain = []
                k = key
                while k is not None:
                    chain.append(graph.fns[k].info.qualname)
                    k = parent[k]
                chain.reverse()
                grouped[ident] = (fn, prim, chain, [line])
            else:
                grouped[ident][3].append(line)
    findings = []
    for ident, (fn, prim, chain, lines) in grouped.items():
        lines.sort()
        also = (f" (also at line{'s' if len(lines) > 2 else ''} "
                + ", ".join(str(ln) for ln in lines[1:]) + ")"
                if len(lines) > 1 else "")
        findings.append(Finding(
            pass_id="blocking", rule="loop-blocking-call",
            ident=ident, file=fn.info.file, line=lines[0],
            message=f"{prim} reachable from the event loop via "
                    + " -> ".join(chain) + also))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings
