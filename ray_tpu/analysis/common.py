"""Shared plumbing for the analyzer passes: the Finding record, file
discovery, and AST helpers used by more than one pass."""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Iterator, Optional

# Directories never scanned: generated protobuf stubs aren't ours to
# lint, and the analyzer itself is full of pattern strings that would
# read as protocol traffic.
_SKIP_DIRS = {"generated", "analysis", "__pycache__"}


@dataclass(frozen=True)
class Finding:
    """One analyzer finding.

    ``ident`` is the stable suppression key used by the baseline file —
    it deliberately contains no line number, so a finding survives
    unrelated edits above it (the same rule clang-tidy NOLINT files and
    ruff baselines follow)."""

    pass_id: str          # protocol | blocking | hotpath | locks
    rule: str             # short rule slug within the pass
    ident: str            # stable suppression id (no line numbers)
    file: str             # repo-relative posix path ("" for module-level)
    line: int             # 1-based line of the finding (0 if n/a)
    message: str          # human-readable description

    def render(self) -> str:
        loc = f"{self.file}:{self.line}" if self.file else "<module>"
        return f"[{self.pass_id}/{self.rule}] {loc}: {self.message}"


def repo_root() -> str:
    """The tree the analyzer lints: the directory containing the
    imported ``ray_tpu`` package."""
    import ray_tpu
    return os.path.dirname(os.path.dirname(os.path.abspath(
        ray_tpu.__file__)))


def rel(path: str, root: str) -> str:
    return os.path.relpath(os.path.abspath(path),
                           os.path.abspath(root)).replace(os.sep, "/")


def iter_py_files(root: str, subdirs: Optional[list] = None
                  ) -> Iterator[str]:
    """Yield .py paths under ``root`` (or root/<subdir> for each given
    subdir), skipping generated/analysis/caches."""
    bases = [os.path.join(root, s) for s in subdirs] if subdirs \
        else [root]
    for base in bases:
        if os.path.isfile(base) and base.endswith(".py"):
            yield base
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in _SKIP_DIRS
                           and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def parse_file(path: str) -> Optional[ast.Module]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return ast.parse(f.read(), filename=path)
    except (OSError, SyntaxError):
        return None


@dataclass
class FuncInfo:
    """One function/method as the passes see it."""

    qualname: str                 # "Class.method" or "func"
    name: str                     # bare name
    file: str                     # repo-relative path
    lineno: int
    node: ast.AST = field(repr=False, default=None)
    class_name: Optional[str] = None
    module_key: str = ""          # file stem, e.g. "node"


class FunctionIndexer(ast.NodeVisitor):
    """Collect every function/method of a module with its enclosing
    class, plus class→bases for MRO-ish resolution.  Nested defs are
    attributed to their outermost enclosing function (a closure defined
    inside a handler runs, for our purposes, as part of it)."""

    def __init__(self, relfile: str, module_key: str):
        self.relfile = relfile
        self.module_key = module_key
        self.functions: dict[str, FuncInfo] = {}
        self.classes: dict[str, list] = {}      # class -> base names
        self.methods: dict[str, dict] = {}      # class -> {name: FuncInfo}
        self._class_stack: list = []
        self._func_depth = 0

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if self._func_depth:
            return  # classes defined inside functions: out of scope
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        self.classes[node.name] = bases
        self.methods.setdefault(node.name, {})
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        if self._func_depth:
            # nested def: body already owned by the outer function
            return
        cls = self._class_stack[-1] if self._class_stack else None
        qual = f"{cls}.{node.name}" if cls else node.name
        info = FuncInfo(qualname=qual, name=node.name, file=self.relfile,
                        lineno=node.lineno, node=node, class_name=cls,
                        module_key=self.module_key)
        self.functions[qual] = info
        if cls:
            self.methods[cls][node.name] = info
        self._func_depth += 1
        self.generic_visit(node)
        self._func_depth -= 1

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def import_aliases(tree: ast.Module) -> dict:
    """Map local alias -> dotted module path for module-level imports
    (``import subprocess``, ``from ray_tpu.core import protocol``,
    ``from ray_tpu.core import fault_injection as _fi``)."""
    out: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                out[a.asname or a.name.split(".")[0]] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                out[a.asname or a.name] = f"{node.module}.{a.name}"
    return out
