"""Registry of disabled-by-default hook sites and their gate contract.

The flight recorder (``_fr``) and the chaos plane (``_fi``) both
promise ZERO overhead when disarmed: every hot-path touch is one
module-global load plus an ``is None`` branch, and nothing else runs on
the disabled path (the committed perf artifacts are the acceptance gate
for that promise).  ``hotpath_pass`` verifies the promise at the
BYTECODE level for every function listed here — and flags any function
in these modules that touches a hook alias *without* being registered,
so a new hook site can't quietly skip the contract.

Modes:

  * ``gate`` — hot path.  Full contract: the alias may only ever be
    dereferenced as ``<alias>._active``, and the function must contain
    an ``is None`` / ``is not None`` test of it (directly or through a
    local: ``rec = _fr._active`` ... ``if rec is None``) with nothing
    between the attribute load and the test.
  * ``use``  — helper only ever called from behind a caller's gate
    (e.g. ``protocol._chaos_filter``).  The alias must still only be
    dereferenced as ``._active``, but no gate is required locally.
  * ``cold`` — setup/teardown code (``__init__`` arming the recorder,
    ``autoinstall_from_env``).  Exempt from the contract, but must be
    listed so the exemption is explicit and reviewed.
"""

from __future__ import annotations

# module import path -> (aliases checked, {qualname: mode})
HOT_GATES: dict = {
    "ray_tpu.core.service": {
        "aliases": ("_fi",),
        "functions": {
            "EventLoopService.run": "gate",          # per-tick chaos hook
            "EventLoopService._dispatch": "gate",    # per-message hook
        },
    },
    "ray_tpu.core.protocol": {
        # _rtf is the native frame codec (core/rt_frames.py): same
        # zero-overhead promise — disarmed, every frame takes the
        # pre-existing pickle path after one load + is-None branch
        "aliases": ("_fi", "_rtf"),
        # the chaos delay call sits inside the armed branch — it never
        # executes disabled, so the registry allows the deref by name
        "extra_attrs": ("apply_delay",),
        "functions": {
            "Connection.enable_ring": "gate",
            "Connection.send": "gate",
            "Connection.send_blob": "gate",
            "Connection.send_batch": "gate",
            "Connection.recv": "gate",
            "_chaos_filter": "use",
            "decode_payload": "gate",
            "dumps_frame": "gate",
        },
    },
    "ray_tpu.core.local_lane": {
        "aliases": ("_fi",),
        "extra_attrs": ("apply_delay",),
        "functions": {
            "LaneConnection._post": "gate",
            "LaneConnection._deliver": "gate",
        },
    },
    # the node service is four modules since the round-12 split (node.py
    # shell + workers/transfer/sched mixins); each module registers the
    # hook sites it now hosts
    "ray_tpu.core.node": {
        "aliases": ("_fi", "_fr"),
        "functions": {
            "NodeService._h_flight_recorder": "gate",
            "NodeService.on_client_drop": "gate",
            # decommission entry point: _fi on_drain trigger at the
            # node_drain push (cold-rate, but the gate discipline is
            # uniform across every hook site)
            "NodeService._hh_node_drain": "gate",
            # arming/teardown — contract-exempt by design
            "NodeService.__init__": "cold",
        },
    },
    "ray_tpu.core.node_sched": {
        "aliases": ("_fi", "_fr", "_rtf"),
        "functions": {
            # flight-recorder lifecycle stamps (hot: every task); the
            # dispatch sites also gate _rtf for the C-side stamp fold
            "NodeSchedMixin._admit_task": "gate",
            "NodeSchedMixin._forward_task": "gate",
            "NodeSchedMixin._make_runnable": "gate",
            "NodeSchedMixin._h_task_done": "gate",
            "NodeSchedMixin._dispatch_task": "gate",  # also _fi kill
            "NodeSchedMixin._h_submit_actor_task": "gate",
            "NodeSchedMixin._dispatch_actor_queue": "gate",
            "NodeSchedMixin._fr_finish": "gate",
        },
    },
    "ray_tpu.core.node_transfer": {
        "aliases": ("_fr", "_fi"),
        "functions": {
            "NodeTransferMixin._hh_node_dead": "gate",
            # decommission handoff: _fi on_drain choke point just
            # before the owned-object migration ships
            "NodeTransferMixin._drain_handoff": "gate",
        },
    },
    "ray_tpu.core.node_workers": {
        "aliases": ("_fi",),
        "functions": {
            "NodeWorkersMixin._spawn_worker_proc": "gate",  # _fi spawn
        },
    },
    "ray_tpu.core.runtime": {
        "aliases": ("_fr",),
        "functions": {
            "Runtime.submit_task_template": "gate",
            "Runtime.submit_actor_task": "gate",
            "Runtime.get": "gate",
        },
    },
    "ray_tpu.core.head": {
        "aliases": ("_fr",),
        "functions": {
            "HeadService._h_cluster_submit": "gate",
            # cluster prefix directory: _fr ingress note per publish
            "HeadService._h_prefix_publish": "gate",
            "HeadService.__init__": "cold",
        },
    },
    # serve fleet ingress: chaos hooks (serve_route / per-stream-chunk
    # serve_stream) and flight-recorder event notes sit on the serving
    # request path — same zero-overhead promise as the control plane:
    # disarmed, each site is one global load + is-None branch.  Both
    # hooks are concentrated in two helper methods so every other fleet
    # function stays alias-free.
    "ray_tpu.serve.fleet.ingress": {
        "aliases": ("_fi", "_fr"),
        "functions": {
            "Fleet.note": "gate",          # _fr event copy when armed
            "Fleet._chaos": "gate",        # _fi serve_* trigger points
        },
    },
    # inference engine: the paged-cache chaos hook (infer_admit /
    # infer_block_alloc / infer_speculate / infer_shard_commit choke
    # points — the last fires after a meshed decode iteration installs
    # the sharded pool arrays) and the
    # flight-recorder request-slice note — one helper each so every
    # other engine function stays alias-free; same zero-overhead
    # promise as the control plane (the decode loop runs them per
    # admission / per block grant / per completed request)
    "ray_tpu.inference.engine": {
        "aliases": ("_fi", "_fr"),
        "functions": {
            "InferenceEngine._chaos": "gate",
            "InferenceEngine._fr_note": "gate",
        },
    },
    # cluster prefix plane: the adoption path's chaos hook
    # (prefix_dir_lookup / prefix_fetch / prefix_install choke points)
    # — one helper so every other plane function stays alias-free; it
    # runs once per routed request when the plane is on, never when off
    "ray_tpu.serve.fleet.prefix_directory": {
        "aliases": ("_fi",),
        "functions": {
            "PrefixPlane._chaos": "gate",
        },
    },
    # serve controller: the drain state machine's chaos hook
    # (replica_drain / replica_drain_timeout choke points) — one helper
    # so every other controller function stays alias-free
    "ray_tpu.serve.controller": {
        "aliases": ("_fi",),
        "functions": {
            "DeploymentState._drain_chaos": "gate",
        },
    },
    # streaming data plane: the operator graph's chaos hook
    # (data_dispatch per block admission, data_shuffle_reduce per
    # reducer dispatch) — one helper on the operator base class so
    # every other executor function stays alias-free; it runs once per
    # block, the hottest data-plane rate
    "ray_tpu.data.execution": {
        "aliases": ("_fi",),
        "functions": {
            "PhysicalOperator._chaos": "gate",
        },
    },
    # trainer streamed ingest: the per-step data_dispatch point on the
    # member-side shard iterator
    "ray_tpu.train.ingest": {
        "aliases": ("_fi",),
        "functions": {
            "DatasetShard._chaos": "gate",
        },
    },
    # elastic gang: the gang_readmit choke point at the re-admission
    # boundary (driver-side, so scripted schedules are deterministic)
    "ray_tpu.parallel.gang": {
        "aliases": ("_fi",),
        "functions": {
            "MultiHostGang._chaos": "gate",
        },
    },
}
