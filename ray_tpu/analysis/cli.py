"""``ray_tpu lint`` — run the control-plane invariant analyzer.

    python -m ray_tpu lint                         # all passes, no baseline
    python -m ray_tpu lint --baseline .lint-baseline.json
    python -m ray_tpu lint --passes protocol,locks
    python -m ray_tpu lint --write-baseline out.json   # bootstrap a baseline
    make lint                                      # repo wiring

Exit codes: 0 clean (after baseline), 1 findings (or stale baseline
entries), 2 usage/config error.
"""

from __future__ import annotations

import json
import os
import sys

from ray_tpu import analysis
from ray_tpu.analysis import baseline as baseline_mod


def run_lint(args) -> int:
    root = args.root or analysis.repo_root()
    passes = tuple(p.strip() for p in args.passes.split(",")) \
        if args.passes else analysis.PASSES
    unknown = [p for p in passes if p not in analysis.PASSES]
    if unknown:
        print(f"unknown pass(es): {', '.join(unknown)} "
              f"(have: {', '.join(analysis.PASSES)})", file=sys.stderr)
        return 2

    findings = analysis.run_passes(root=root, passes=passes)

    if args.write_baseline:
        baseline_mod.write(findings, args.write_baseline)
        print(f"wrote {len({f.ident for f in findings})} baseline "
              f"entries to {args.write_baseline} — fill in the "
              f"justifications")
        return 0

    baseline_path = args.baseline
    if baseline_path and getattr(args, "no_baseline", False):
        print("--baseline and --no-baseline conflict — pick one",
              file=sys.stderr)
        return 2
    if baseline_path is None and not getattr(args, "no_baseline", False):
        # default to the linted tree's committed baseline, so the bare
        # `ray_tpu lint` agrees with `make lint` and tier-1 instead of
        # re-reporting every reviewed suppression
        candidate = os.path.join(root, baseline_mod.DEFAULT_BASELINE)
        if os.path.exists(candidate):
            baseline_path = candidate
    bl = {}
    if baseline_path:
        if not os.path.exists(baseline_path):
            print(f"baseline file not found: {baseline_path}",
                  file=sys.stderr)
            return 2
        try:
            bl = baseline_mod.load(baseline_path)
        except ValueError as e:
            print(f"bad baseline: {e}", file=sys.stderr)
            return 2
        # entries for passes NOT selected this run can't match anything
        # — without this filter `--passes protocol` would call every
        # other pass's suppression stale and tell the user to delete it
        bl = {i: j for i, j in bl.items()
              if i.split(":", 1)[0] in passes}
    active, suppressed, stale = baseline_mod.apply(findings, bl)

    if args.json:
        print(json.dumps({
            "active": [vars(f) for f in active],
            "suppressed": [vars(f) for f in suppressed],
            "stale_baseline_ids": stale,
        }, indent=2))
    else:
        for f in active:
            print(f.render())
        for ident in stale:
            print(f"[baseline/stale] {ident}: baselined but no longer "
                  f"reported — remove the entry")
        counts = {}
        for f in active:
            counts[f.pass_id] = counts.get(f.pass_id, 0) + 1
        per_pass = ", ".join(f"{p}={counts.get(p, 0)}" for p in passes)
        print(f"lint: {len(active)} finding"
              f"{'s' if len(active) != 1 else ''} "
              f"({len(suppressed)} baselined, {len(stale)} stale "
              f"baseline entr{'ies' if len(stale) != 1 else 'y'}) "
              f"[{per_pass}]")
    return 1 if (active or stale) else 0


def add_parser(sub) -> None:
    p = sub.add_parser(
        "lint", help="static control-plane invariant analysis "
                     "(protocol drift, event-loop blocking, hot-path "
                     "gates, lock-held I/O)")
    p.add_argument("--baseline", default=None,
                   help="suppress findings listed (with justification) "
                        "in this JSON file (default: the linted tree's "
                        ".lint-baseline.json when present)")
    p.add_argument("--no-baseline", action="store_true",
                   help="report raw findings, ignoring any committed "
                        "baseline")
    p.add_argument("--passes", default=None,
                   help="comma-separated subset of: "
                        + ",".join(analysis.PASSES))
    p.add_argument("--root", default=None,
                   help="repo root to lint (default: the tree the "
                        "imported ray_tpu package lives in)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")
    p.add_argument("--write-baseline", default=None, metavar="PATH",
                   help="write current findings as a baseline skeleton "
                        "and exit 0")
    p.set_defaults(fn=run_lint)
