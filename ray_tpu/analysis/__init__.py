"""Control-plane invariant analyzer (``ray_tpu lint``).

Four static passes over the control plane, each enforcing an invariant
that a past PR shipped a bug against (see ARCHITECTURE.md
"Control-plane invariants"):

  * protocol   — every literal ``{"t": ...}`` message type sent anywhere
                 in the package has a handler (``_h_*`` / ``_hh_*`` /
                 client-side dispatch), and every defined handler has a
                 sender: the ``getattr(self, "_h_" + t)`` dispatch makes
                 drift silent at runtime.
  * blocking   — no ``time.sleep`` / blocking socket / ``subprocess`` /
                 ``waitpid``-without-WNOHANG call is reachable from an
                 event-loop entry point (``_h_*`` handlers, ``on_tick``,
                 ``_dispatch``): one blocking call stalls a whole node.
  * hotpath    — every registered disabled-by-default hook (flight
                 recorder, fault injection) compiles to a module-global
                 load + ``is None`` branch and nothing else on the
                 disabled path (bytecode-verified).
  * locks      — no file/socket write, pickle, or ``send*`` call runs
                 lexically inside a ``with <lock>:`` block unless
                 baselined with a justification.

The reference codebase leans on C++ sanitizers and clang-tidy for this
class of invariant; our control plane is Python, so the AST/``dis``
passes live here.  Findings are suppressible via a checked-in baseline
(``.lint-baseline.json``) carrying a per-finding justification; the
suite runs in tier-1 (``tests/test_lint_clean.py``) so regressions fail
CI, and ``python -m ray_tpu lint`` runs it from the command line.
"""

from __future__ import annotations

from ray_tpu.analysis.common import Finding, repo_root
from ray_tpu.analysis import (baseline, blocking_pass, hotpath_pass,
                              locks_pass, protocol_pass)

PASSES = ("protocol", "blocking", "hotpath", "locks")


def run_passes(root=None, passes=PASSES) -> list:
    """Run the selected passes over the repo at ``root`` (default: the
    tree containing the imported ray_tpu package) and return the
    combined, sorted finding list (unsuppressed — apply a baseline with
    ``baseline.apply``)."""
    import os as _os
    root = root or repo_root()
    findings: list[Finding] = []
    if "protocol" in passes:
        findings += protocol_pass.run(root)
    if "blocking" in passes:
        findings += blocking_pass.run(root)
    if "hotpath" in passes:
        # the hotpath pass checks COMPILED bytecode, so it can only ever
        # see the imported ray_tpu package — running it against some
        # other tree would silently report on the wrong code
        if _os.path.realpath(root) == _os.path.realpath(repo_root()):
            findings += hotpath_pass.run()
        else:
            findings.append(Finding(
                pass_id="hotpath", rule="skipped-foreign-root",
                ident="hotpath:skipped-foreign-root",
                file="", line=0,
                message=f"hotpath pass checks the IMPORTED ray_tpu "
                        f"package's bytecode and cannot lint {root!r}; "
                        f"run it from that tree's own interpreter "
                        f"(or drop it via --passes)"))
    if "locks" in passes:
        findings += locks_pass.run(root)
    findings.sort(key=lambda f: (f.pass_id, f.file, f.line, f.ident))
    return findings


__all__ = ["Finding", "PASSES", "run_passes", "repo_root", "baseline",
           "protocol_pass", "blocking_pass", "hotpath_pass", "locks_pass"]
