"""Baseline file: suppress known, *justified* findings.

``.lint-baseline.json`` is checked in at the repo root.  Every entry
must carry a non-empty justification — the baseline is a reviewed list
of decisions ("this lock-held send IS the point of the lock"), not a
mute button.  Stale entries (nothing matches them anymore) are reported
so the file tracks reality; ``tests/test_lint_clean.py`` fails on them.

Format:

    {"findings": [
        {"id": "locks:ray_tpu/core/protocol.py:send:.sendall()",
         "justification": "the per-connection wire lock exists to ..."}
    ]}
"""

from __future__ import annotations

import json
from typing import Optional

DEFAULT_BASELINE = ".lint-baseline.json"


def load(path: str) -> dict:
    """-> {ident: justification}.  Raises ValueError on entries missing
    a justification (an unexplained suppression is itself a finding)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    out = {}
    for entry in data.get("findings", []):
        ident = entry.get("id", "")
        just = (entry.get("justification") or "").strip()
        if not ident:
            raise ValueError("baseline entry missing 'id'")
        if not just or just.upper().startswith("TODO"):
            raise ValueError(
                f"baseline entry {ident!r} has no real justification "
                f"(empty or TODO placeholder) — every suppression must "
                f"say why")
        out[ident] = just
    return out


def apply(findings: list, baseline: Optional[dict]) -> tuple:
    """-> (active, suppressed, stale_ids)."""
    baseline = baseline or {}
    active = [f for f in findings if f.ident not in baseline]
    suppressed = [f for f in findings if f.ident in baseline]
    matched = {f.ident for f in suppressed}
    stale = sorted(i for i in baseline if i not in matched)
    return active, suppressed, stale


def write(findings: list, path: str,
          justification: str = "TODO: justify or fix") -> None:
    """Emit a baseline covering ``findings`` (dedup by ident).  Used by
    ``ray_tpu lint --write-baseline``.  Justifications already present
    in the file at ``path`` are PRESERVED — refreshing a baseline in
    place must not destroy its reviewed entries — and only genuinely
    new idents get the TODO placeholder, which MUST be filled in before
    commit: ``load()`` rejects it, so a skeleton committed as-is fails
    tier-1 instead of muting findings."""
    existing = {}
    try:
        with open(path, "r", encoding="utf-8") as f:
            for entry in json.load(f).get("findings", []):
                if entry.get("id") and entry.get("justification"):
                    existing[entry["id"]] = entry["justification"]
    except (OSError, ValueError):
        pass
    seen = {}
    for f in findings:
        seen.setdefault(f.ident, f)
    data = {"findings": [
        {"id": ident, "finding": seen[ident].render(),
         "justification": existing.get(ident, justification)}
        for ident in sorted(seen)]}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
