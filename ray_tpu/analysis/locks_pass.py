"""Pass 4: I/O (or pickling) lexically inside ``with <lock>:`` blocks.

PR 3 had to move span file writes out from under the tracing buffer
lock — every tracer in the process was serializing behind the disk.
The same shape (grab a lock, then write to a file/socket or pickle a
large object while holding it) turns a lock that should bound
microseconds of mutation into one that bounds milliseconds of I/O, and
on the control plane it can deadlock outright when the I/O blocks on
the very loop that needs the lock.

The pass scans ``ray_tpu/core/`` and ``ray_tpu/util/tracing.py`` for
``with`` statements whose context expression *names a lock* (terminal
identifier containing "lock", case-insensitive — matching this repo's
uniform naming) and flags, lexically inside the block body:

  * socket/file write calls by attribute name (``send``, ``sendall``,
    ``send_batch``, ``send_blob``, ``sendto``, ``write``,
    ``writelines``, ``flush``)
  * pickling/encoding: ``pickle``/``cloudpickle``/``json``/``marshal``
    ``dump[s]``/``load[s]`` through an import alias, and the protocol
    encoders (``dumps_frame``, ``encode_payload``, ``blob_frame_parts``)
  * file-system mutation: builtin ``open`` and ``os.write/replace/
    rename/unlink/fsync/makedirs``
  * calls to same-file helpers whose bodies directly contain any of the
    above (one level deep — catches ``_drain_locked()``-style splits)

Deliberate holds (a dedicated wire lock whose *purpose* is serializing
the write) stay, baselined with a justification — the point is that
every lock-held write is a decision someone wrote down, not an
accident.
"""

from __future__ import annotations

import ast
from typing import Optional

from ray_tpu.analysis.common import (Finding, import_aliases,
                                     iter_py_files, parse_file, rel,
                                     repo_root)

DEFAULT_TARGETS = ["ray_tpu/core", "ray_tpu/util/tracing.py"]

_IO_ATTRS = {"send", "sendall", "send_batch", "send_blob", "sendto",
             "write", "writelines", "flush"}
_PICKLE_MODULES = {"pickle", "cloudpickle", "json", "marshal"}
_PICKLE_ATTRS = {"dump", "dumps", "load", "loads"}
_ENCODER_NAMES = {"dumps_frame", "encode_payload", "blob_frame_parts"}
_OS_ATTRS = {"write", "replace", "rename", "unlink", "fsync", "makedirs"}


def _terminal_name(node) -> Optional[str]:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Call):
        return _terminal_name(node.func)
    return None


def _is_lock_expr(node) -> bool:
    name = _terminal_name(node)
    return name is not None and "lock" in name.lower()


def _local_imports(fn_node) -> dict:
    """Function-local ``import pickle`` style aliases."""
    out = {}
    for n in ast.walk(fn_node):
        if isinstance(n, ast.Import):
            for a in n.names:
                out[a.asname or a.name.split(".")[0]] = a.name
    return out


class _FileScan(ast.NodeVisitor):
    def __init__(self, relfile: str, aliases: dict):
        self.relfile = relfile
        self.aliases = aliases
        self.func_stack: list = []
        self.lock_stack: list = []        # lock source names
        self.hits: list = []              # (func, lock, what, line)
        # first pass fills this: helper name -> direct primitive labels
        self.helper_io: dict = {}

    # -- structure ----------------------------------------------------

    def _visit_func(self, node) -> None:
        self.func_stack.append(node.name)
        merged = dict(self.aliases)
        merged.update(_local_imports(node))
        old, self.aliases = self.aliases, merged
        # a def nested under `with lock:` runs LATER, off-lock (it's a
        # deferred callback) — its body must not inherit the lock scope
        saved_locks, self.lock_stack = self.lock_stack, []
        self.generic_visit(node)
        self.lock_stack = saved_locks
        self.aliases = old
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved_locks, self.lock_stack = self.lock_stack, []
        self.generic_visit(node)
        self.lock_stack = saved_locks

    def visit_With(self, node: ast.With) -> None:
        lock_idx = next((i for i, it in enumerate(node.items)
                         if _is_lock_expr(it.context_expr)), None)
        if lock_idx is not None:
            # items BEFORE the lock enter first (lock not yet held);
            # items AFTER it — `with self._lock, open(p) as f:` — run
            # while holding it, exactly like the body
            for it in node.items[:lock_idx]:
                self.visit(it.context_expr)
            self.lock_stack.append(
                ast.unparse(node.items[lock_idx].context_expr))
            for it in node.items[lock_idx + 1:]:
                self.visit(it.context_expr)
            for stmt in node.body:
                self.visit(stmt)
            self.lock_stack.pop()
            return
        self.generic_visit(node)

    # -- classification -----------------------------------------------

    def _classify(self, node: ast.Call) -> Optional[str]:
        f = node.func
        if isinstance(f, ast.Name):
            target = self.aliases.get(f.id, f.id)
            if f.id == "open" or target == "open":
                return "open()"
            if f.id in _ENCODER_NAMES or target.rsplit(".", 1)[-1] \
                    in _ENCODER_NAMES:
                return f"{f.id}() (pickles the message)"
            return None
        if not isinstance(f, ast.Attribute):
            return None
        attr = f.attr
        if isinstance(f.value, ast.Name):
            mod = self.aliases.get(f.value.id, "").split(".")[0]
            if mod in _PICKLE_MODULES and attr in _PICKLE_ATTRS:
                return f"{mod}.{attr}"
            if mod == "os" and attr in _OS_ATTRS:
                return f"os.{attr}"
            if mod in _PICKLE_MODULES or mod == "os":
                return None   # other calls on these modules: not I/O
        if attr in _IO_ATTRS:
            return f".{attr}()"
        return None

    def visit_Call(self, node: ast.Call) -> None:
        if self.lock_stack:
            what = self._classify(node)
            if what is None:
                # one-level helper expansion: same-file function whose
                # body does direct I/O
                name = None
                f = node.func
                if isinstance(f, ast.Name):
                    name = f.id
                elif isinstance(f, ast.Attribute) \
                        and isinstance(f.value, ast.Name) \
                        and f.value.id == "self":
                    name = f.attr
                if name in self.helper_io:
                    what = (f"{name}() (does "
                            f"{', '.join(self.helper_io[name])})")
            if what is not None:
                func = self.func_stack[-1] if self.func_stack \
                    else "<module>"
                self.hits.append((func, self.lock_stack[-1], what,
                                  node.lineno))
        self.generic_visit(node)


def _collect_helper_io(tree, relfile: str, aliases: dict) -> dict:
    """Map function name -> labels of direct I/O primitives in its body
    (ignoring lock context — used for the one-level expansion).  Walks
    with its own visitor; only the classifier is borrowed."""
    scan = _FileScan(relfile, aliases)
    out: dict = {}

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def _fn(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _fn
        visit_AsyncFunctionDef = _fn

        def visit_Call(self, node):
            if self.stack:
                what = scan._classify(node)
                if what is not None:
                    out.setdefault(self.stack[-1], [])
                    if what not in out[self.stack[-1]]:
                        out[self.stack[-1]].append(what)
            self.generic_visit(node)

    V().visit(tree)
    return out


def run(root: Optional[str] = None,
        targets: Optional[list] = None) -> list:
    root = root or repo_root()
    findings = []
    for path in iter_py_files(root, targets or DEFAULT_TARGETS):
        tree = parse_file(path)
        if tree is None:
            continue
        relfile = rel(path, root)
        aliases = import_aliases(tree)
        scan = _FileScan(relfile, aliases)
        scan.helper_io = _collect_helper_io(tree, relfile, aliases)
        scan.visit(tree)
        for func, lock, what, line in scan.hits:
            findings.append(Finding(
                pass_id="locks", rule="io-under-lock",
                ident=f"locks:{relfile}:{func}:{what.split(' ')[0]}",
                file=relfile, line=line,
                message=f"{func} calls {what} while holding {lock}"))
    findings.sort(key=lambda f: (f.file, f.line))
    return findings
