"""Pass 1: protocol consistency.

The control plane dispatches by name — ``getattr(self, "_h_" + msg["t"],
None)`` in ``core/service.py`` and ``getattr(self, "_hh_" + m["t"],
None)`` for head pushes in ``core/node.py`` — so a renamed handler or a
typo'd message type fails *silently*: the message is dropped (or dies
with "unknown message" only when the sender asked for a reply).  PR 2's
split-brain hid behind exactly this kind of drift.

This pass cross-references, across the whole package:

  * **sent types** — every literal ``{"t": "<type>", ...}`` dict and
    every ``x["t"] = "<type>"`` assignment (messages are always built as
    literals at the send site; forwarding reuses an existing dict and
    introduces no new types), and
  * **handled types** — every ``_h_<type>`` / ``_hh_<type>`` method
    (server side: service.py's ClusterStoreMixin + EventLoopService,
    head.py, node.py) and every string the code compares against a
    message's ``"t"`` field (client side: client.py reply routing,
    executor.py's run loop, observer.py's reply matching, node.py's
    peer dispatch), including comparisons through a local alias
    (``t = msg.get("t")`` ... ``t == "execute"``).

and reports types sent with no handler anywhere, and ``_h_*``/``_hh_*``
handlers no code path sends (dead handlers — usually a removed feature
or a test-only RPC; the latter gets baselined with a justification).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.analysis.common import (Finding, iter_py_files, parse_file,
                                     rel, repo_root)

HANDLER_PREFIXES = ("_h_", "_hh_")

# Scan scope for SEND sites and client-side dispatch comparisons: the
# whole package (the CLI, dashboard, and util helpers all speak the
# protocol).  ``_h_*``/``_hh_*`` HANDLER DEFINITIONS are only collected
# from the protocol services under core/ — elsewhere the prefix is just
# a naming coincidence (rllib's value-rescaling ``_h_inv`` is math, not
# a message handler).
DEFAULT_SUBDIRS = ["ray_tpu"]
HANDLER_DEF_PREFIX = "ray_tpu/core/"

# Files whose ``t == "..."`` comparisons are CODEC dispatch (choosing a
# wire encoding arm), not message consumption — counting them as
# handlers would mask a genuinely dropped handler behind the encoder.
MATCH_EXCLUDE = ("ray_tpu/core/schema.py",)


@dataclass
class ProtocolReport:
    """Raw cross-reference tables, exposed for tests and tooling."""

    sends: dict = field(default_factory=dict)      # type -> [(file, line)]
    handlers: dict = field(default_factory=dict)   # type -> [(file, line, how)]
    unhandled: list = field(default_factory=list)  # sorted types
    dead: list = field(default_factory=list)       # [(type, file, line)]

    def handler_files(self) -> set:
        return {f for locs in self.handlers.values() for (f, _, _) in locs}


def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_t_lookup(node) -> bool:
    """``<expr>.get("t")`` / ``<expr>.get("t", default)`` or
    ``<expr>["t"]``."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        return _const_str(node.args[0]) == "t"
    if isinstance(node, ast.Subscript):
        sl = node.slice
        return _const_str(sl) == "t"
    return False


class _Collector(ast.NodeVisitor):
    def __init__(self, relfile: str, report: ProtocolReport,
                 collect_defs: bool = True, collect_matches: bool = True):
        self.relfile = relfile
        self.report = report
        self.collect_defs = collect_defs
        self.collect_matches = collect_matches
        self._tvars: list[set] = []   # per-function: names aliasing msg["t"]

    # -- send sites ---------------------------------------------------

    def visit_Dict(self, node: ast.Dict) -> None:
        for k, v in zip(node.keys, node.values):
            if k is not None and _const_str(k) == "t":
                t = _const_str(v)
                if t is not None:
                    self.report.sends.setdefault(t, []).append(
                        (self.relfile, node.lineno))
        self.generic_visit(node)

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) \
                    and _const_str(tgt.slice) == "t":
                t = _const_str(node.value)
                if t is not None:
                    self.report.sends.setdefault(t, []).append(
                        (self.relfile, node.lineno))
            # t = msg.get("t") — remember the alias for comparisons
            if self._tvars and isinstance(tgt, ast.Name) \
                    and _is_t_lookup(node.value):
                self._tvars[-1].add(tgt.id)
        self.generic_visit(node)

    # -- handler sites ------------------------------------------------

    def _visit_func(self, node) -> None:
        for prefix in HANDLER_PREFIXES if self.collect_defs else ():
            if node.name.startswith(prefix):
                t = node.name[len(prefix):]
                self.report.handlers.setdefault(t, []).append(
                    (self.relfile, node.lineno, "def " + node.name))
        self._tvars.append(set())
        self.generic_visit(node)
        self._tvars.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def _is_t_ref(self, node) -> bool:
        if _is_t_lookup(node):
            return True
        return (isinstance(node, ast.Name) and self._tvars
                and node.id in self._tvars[-1])

    def _note_handled(self, node, lineno: int) -> None:
        t = _const_str(node)
        if t is not None:
            self.report.handlers.setdefault(t, []).append(
                (self.relfile, lineno, "match"))
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for el in node.elts:
                self._note_handled(el, lineno)

    def visit_Compare(self, node: ast.Compare) -> None:
        if self.collect_matches and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.Eq, ast.In)):
            left, right = node.left, node.comparators[0]
            if self._is_t_ref(left):
                self._note_handled(right, node.lineno)
            elif self._is_t_ref(right):
                self._note_handled(left, node.lineno)
        self.generic_visit(node)


def collect(root: Optional[str] = None,
            subdirs: Optional[list] = None,
            handler_def_prefix: Optional[str] = None) -> ProtocolReport:
    """Build the send/handler cross-reference for the tree at ``root``.

    ``handler_def_prefix`` limits where ``def _h_*`` counts as a handler
    ("" = everywhere, for fixture trees)."""
    root = root or repo_root()
    if handler_def_prefix is None:
        handler_def_prefix = HANDLER_DEF_PREFIX
    report = ProtocolReport()
    for path in iter_py_files(root, subdirs or DEFAULT_SUBDIRS):
        tree = parse_file(path)
        if tree is None:
            continue
        relfile = rel(path, root)
        _Collector(relfile, report,
                   collect_defs=relfile.startswith(handler_def_prefix),
                   collect_matches=relfile not in MATCH_EXCLUDE
                   ).visit(tree)
    report.unhandled = sorted(t for t in report.sends
                              if t not in report.handlers)
    report.dead = sorted(
        (t, f, ln)
        for t, locs in report.handlers.items() if t not in report.sends
        for (f, ln, how) in locs if how.startswith("def "))
    return report


def run(root: Optional[str] = None,
        subdirs: Optional[list] = None,
        handler_def_prefix: Optional[str] = None) -> list:
    report = collect(root, subdirs,
                     handler_def_prefix=handler_def_prefix)
    findings = []
    for t in report.unhandled:
        f, ln = report.sends[t][0]
        n = len(report.sends[t])
        findings.append(Finding(
            pass_id="protocol", rule="unhandled-message-type",
            ident=f"protocol:unhandled:{t}",
            file=f, line=ln,
            message=f'message type "{t}" is sent ({n} site'
                    f'{"s" if n > 1 else ""}) but no _h_/_hh_ handler or '
                    f'client-side dispatch matches it'))
    for t, f, ln in report.dead:
        findings.append(Finding(
            pass_id="protocol", rule="dead-handler",
            ident=f"protocol:dead-handler:{t}:{f}",
            file=f, line=ln,
            message=f'handler for "{t}" defined here but nothing in the '
                    f'package sends that message type'))
    return findings
