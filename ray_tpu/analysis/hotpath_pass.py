"""Pass 3: hot-path gate discipline, verified at the bytecode level.

Generalizes the one-off ``dis``-based test PR 3 wrote for the flight
recorder's disabled path into a reusable pass over every registered
disabled-by-default hook (``hotpath_registry.HOT_GATES``): the flight
recorder and fault-injection hooks on the dispatch/send/recv hot paths.

For a registered ``gate`` function the pass asserts, on the compiled
bytecode (nested code objects — closures, comprehensions — included):

  1. the hook alias (``_fr`` / ``_fi``) is only ever dereferenced as
     ``<alias>._active`` — no method calls, no other attributes: the
     disabled path must not pay an extra lookup or a call;
  2. at least one genuine ``is None`` gate exists: either
     ``<alias>._active is [not] None`` with nothing between the
     attribute load and the comparison, or the store-then-test shape
     ``x = <alias>._active`` ... ``x is [not] None``.

``use`` functions get rule 1 only (they run behind a caller's gate);
``cold`` functions are exempt but must be listed.  Any OTHER function
in a registered module that touches a hook alias is reported — new hook
sites must register, which is how the contract stays enforced instead
of remembered.
"""

from __future__ import annotations

import dis
import importlib
import types
from typing import Iterator, Optional

from ray_tpu.analysis.common import Finding
from ray_tpu.analysis.hotpath_registry import HOT_GATES

_LOADS = ("LOAD_GLOBAL", "LOAD_NAME")
_ATTR_LOADS = ("LOAD_ATTR", "LOAD_METHOD")
# 3.11+ fuses `is None` jumps into one opcode
_NONE_JUMPS = ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE",
               "POP_JUMP_FORWARD_IF_NONE", "POP_JUMP_FORWARD_IF_NOT_NONE")


def _iter_codes(code) -> Iterator:
    yield code
    for const in code.co_consts:
        if isinstance(const, types.CodeType):
            yield from _iter_codes(const)


def _none_test_polarity(instrs, j):
    """If ``instrs[j:]`` starts an ``is [not] None`` test of the value
    on the stack, return ``(jump_index, guards_fallthrough)`` —
    ``guards_fallthrough`` True means the NOT-None case falls through
    (the code between the jump and its target runs armed).  None when
    it isn't a None test."""
    a = instrs[j] if j < len(instrs) else None
    if a is None:
        return None
    if a.opname in _NONE_JUMPS:
        # POP_JUMP_[FORWARD_]IF_NONE jumps AWAY on None
        return j, "IF_NONE" in a.opname
    b = instrs[j + 1] if j + 1 < len(instrs) else None
    c = instrs[j + 2] if j + 2 < len(instrs) else None
    if a.opname == "LOAD_CONST" and a.argval is None \
            and b is not None and b.opname == "IS_OP" \
            and c is not None and c.opname.startswith("POP_JUMP"):
        is_not = bool(b.arg)                 # IS_OP 1 == `is not`
        jump_on_true = "IF_TRUE" in c.opname
        # fall-through runs the not-None arm when the jump is taken on
        # the None outcome: (`is not` + jump-on-false) or
        # (`is` + jump-on-true)
        return j + 2, is_not != jump_on_true
    return None


def _check_code(code, alias: str, mode: str,
                extra_attrs: tuple = ()) -> list:
    """Return problem strings for one code object.  Every ``_active``
    load site is judged INDIVIDUALLY: a gate site opens a guarded
    region, a use site (``<alias>._active.meth(...)``) must sit inside
    one, and a store site's local must be None-tested somewhere — one
    gated touch must not launder an ungated one elsewhere in the same
    function (that shape crashes the moment the hook is disabled).
    ``extra_attrs`` names attributes the registry explicitly allows
    besides ``_active`` (e.g. ``apply_delay`` for the chaos delay
    inside an armed branch)."""
    problems = []
    for co in _iter_codes(code):
        if alias not in co.co_names:
            continue
        # EXTENDED_ARG prefixes (big functions: const/jump args > 255)
        # are already folded into the next instruction's argval by dis —
        # drop them so pattern stepping sees the logical sequence
        instrs = [ins for ins in dis.get_instructions(co)
                  if ins.opname != "EXTENDED_ARG"]
        regions: list = []    # (lo_offset, hi_offset) proven-armed code

        def note_gate(jump_idx, guards_fallthrough):
            jump = instrs[jump_idx]
            target = jump.argval          # jump target byte offset
            if jump_idx + 1 >= len(instrs):
                return
            here = instrs[jump_idx + 1].offset
            if guards_fallthrough:
                # fall-through arm runs only when _active is not None
                regions.append((here, target))
            else:
                # fall-through arm handles None; if it unconditionally
                # exits (early-return shape), everything from the jump
                # target onward runs armed
                arm = [x for x in instrs if here <= x.offset < target]
                if arm and arm[-1].opname in ("RETURN_VALUE",
                                              "RAISE_VARARGS", "RERAISE",
                                              "RETURN_CONST"):
                    regions.append((target, float("inf")))

        # phase 1: which locals are bound from `<alias>._active`?  Only
        # THEIR None-tests open armed regions — an unrelated guard
        # (`if spec is not None:`) proves nothing about the hook
        bound_locals: set = set()
        for i, ins in enumerate(instrs):
            if ins.opname in _LOADS and ins.argval == alias \
                    and i + 2 < len(instrs) \
                    and instrs[i + 1].opname in _ATTR_LOADS \
                    and instrs[i + 1].argval == "_active" \
                    and instrs[i + 2].opname == "STORE_FAST":
                bound_locals.add(instrs[i + 2].argval)

        gate_count = 0
        store_sites: list = []   # (local_name, line)
        use_sites: list = []     # (byte_offset, line)
        tested_locals: set = set()
        cur_line = co.co_firstlineno
        for i, ins in enumerate(instrs):
            # 3.13 renamed the int-valued field to line_number and made
            # starts_line a bool
            ln = getattr(ins, "line_number", None)
            if ln is None and not isinstance(ins.starts_line, bool):
                ln = ins.starts_line
            if ln is not None:
                cur_line = ln
            if ins.opname == "LOAD_FAST":
                if ins.argval not in bound_locals:
                    continue
                t = _none_test_polarity(instrs, i + 1)
                if t is not None:
                    tested_locals.add(ins.argval)
                    note_gate(*t)
                elif i + 1 < len(instrs) \
                        and instrs[i + 1].opname == "RETURN_VALUE":
                    pass   # returning the (possibly None) recorder is safe
                else:
                    # a USE of the bound local: must sit in a guarded
                    # region like a direct `_active` use — a None test
                    # somewhere else must not launder this site
                    use_sites.append((ins.offset, cur_line))
                continue
            if not (ins.opname in _LOADS and ins.argval == alias):
                continue
            nxt = instrs[i + 1] if i + 1 < len(instrs) else None
            if nxt is not None and nxt.opname in _ATTR_LOADS \
                    and nxt.argval in extra_attrs:
                continue
            if nxt is None or nxt.opname not in _ATTR_LOADS \
                    or nxt.argval != "_active":
                what = (f"{alias}.{nxt.argval}" if nxt is not None
                        and nxt.opname in _ATTR_LOADS else alias)
                problems.append(
                    f"dereferences {what!r} at line {cur_line} — the "
                    f"only allowed touch is `{alias}._active`")
                continue
            t = _none_test_polarity(instrs, i + 2)
            if t is not None:
                gate_count += 1
                note_gate(*t)
            elif i + 2 < len(instrs) \
                    and instrs[i + 2].opname == "STORE_FAST":
                store_sites.append((instrs[i + 2].argval, cur_line,
                                    ins.offset))
            else:
                use_sites.append((ins.offset, cur_line))
        if mode != "gate":
            # "use" helpers run behind their CALLER's gate: only the
            # deref rule applies; an untested local bind is their normal
            # shape (`plan = _fi._active` in _chaos_filter)
            continue
        for local, line, off in store_sites:
            if local in tested_locals:
                gate_count += 1
            elif not any(lo <= off < hi for lo, hi in regions):
                # a store inside an already-guarded region (re-reading
                # the global after an early-return gate) needs no second
                # test; an unguarded, untested one is a disabled-path
                # crash
                problems.append(
                    f"binds `{local} = {alias}._active` at line {line} "
                    f"but never None-tests it — crashes when the hook "
                    f"is disabled")
        for off, line in use_sites:
            if not any(lo <= off < hi for lo, hi in regions):
                problems.append(
                    f"uses `{alias}._active` at line {line} outside any "
                    f"`is None`-guarded branch — crashes when the hook "
                    f"is disabled")
        if mode == "gate" and gate_count == 0 and not problems:
            problems.append(
                f"touches `{alias}._active` but has no `is None` gate "
                f"(direct or through a local)")
    return problems


def _functions_of(mod) -> dict:
    """{qualname: function} for module functions and class methods."""
    out = {}
    for name, obj in vars(mod).items():
        if isinstance(obj, types.FunctionType) \
                and obj.__module__ == mod.__name__:
            out[name] = obj
        elif isinstance(obj, type) and obj.__module__ == mod.__name__:
            for mname, mobj in vars(obj).items():
                fn = mobj
                if isinstance(fn, (staticmethod, classmethod)):
                    fn = fn.__func__
                if isinstance(fn, types.FunctionType):
                    out[f"{name}.{mname}"] = fn
    return out


def check_module(module_path: str, aliases: tuple, functions: dict,
                 mod=None, extra_attrs: tuple = ()) -> list:
    """Check one module against its registry entry.  ``mod`` may be a
    pre-built module object (fixture tests)."""
    if mod is None:
        mod = importlib.import_module(module_path)
    relfile = module_path.replace(".", "/") + ".py"
    findings = []
    for qual, fn in sorted(_functions_of(mod).items()):
        code = fn.__code__
        touched = [a for a in aliases
                   if any(a in co.co_names for co in _iter_codes(code))]
        if not touched:
            continue
        mode = functions.get(qual)
        if mode is None:
            findings.append(Finding(
                pass_id="hotpath", rule="unregistered-gate-site",
                ident=f"hotpath:unregistered:{module_path}:{qual}",
                file=relfile, line=code.co_firstlineno,
                message=f"{qual} touches {'/'.join(touched)} but is not "
                        f"in hotpath_registry.HOT_GATES — register it "
                        f"(gate/use/cold) so the disabled-path contract "
                        f"is explicit"))
            continue
        if mode == "cold":
            continue
        for alias in touched:
            for prob in _check_code(code, alias, mode, extra_attrs):
                findings.append(Finding(
                    pass_id="hotpath", rule="fat-disabled-path",
                    ident=f"hotpath:gate:{module_path}:{qual}:{alias}",
                    file=relfile, line=code.co_firstlineno,
                    message=f"{qual}: {prob}"))
    # registry entries that no longer exist are drift too
    have = set(_functions_of(mod))
    for qual in functions:
        if qual not in have:
            findings.append(Finding(
                pass_id="hotpath", rule="stale-registry-entry",
                ident=f"hotpath:stale:{module_path}:{qual}",
                file=relfile, line=0,
                message=f"hotpath_registry lists {qual} but the module "
                        f"no longer defines it"))
    return findings


def run(registry: Optional[dict] = None) -> list:
    registry = registry if registry is not None else HOT_GATES
    findings = []
    for module_path, entry in sorted(registry.items()):
        findings += check_module(
            module_path, tuple(entry["aliases"]),
            dict(entry["functions"]),
            extra_attrs=tuple(entry.get("extra_attrs", ())))
    return findings
