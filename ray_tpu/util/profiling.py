"""Sampling profiler + flamegraphs, dependency-free.

Reference capability: the dashboard's on-demand py-spy profiling
(reference: dashboard/modules/reporter/profile_manager.py:11-14 — wraps
the py-spy binary for flamegraphs of live workers).  py-spy is an
external Rust tool; here the sampler is in-process — a thread walks
``sys._current_frames()`` at a fixed rate and aggregates FOLDED stacks
(the flamegraph interchange format), and a small deterministic SVG
renderer turns them into a self-contained flamegraph.  In-process
sampling sees exactly the interpreter's Python frames (it cannot profile
a foreign pid like py-spy; the node routes profile requests to each
worker instead, core/executor.py "profile").
"""

from __future__ import annotations

import sys
import threading
import time
from collections import Counter
from typing import Optional

_EXCLUDE_THREADS = ("raytpu-recv", "raytpu-autoflush", "raytpu-sampler",
                    "raytpu-devmat")


def sample_folded(duration: float = 2.0, hz: float = 99.0,
                  all_threads: bool = True,
                  target_thread: Optional[int] = None) -> str:
    """Sample this process's Python stacks for ``duration`` seconds.

    Returns folded-stack lines: ``mod.func;mod.func2;... COUNT`` —
    the flamegraph.pl / speedscope interchange format."""
    counts: Counter = Counter()
    interval = 1.0 / hz
    me = threading.get_ident()
    names = {}
    deadline = time.monotonic() + duration
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            if tid == me or (target_thread and tid != target_thread):
                continue
            th = names.get(tid)
            if th is None:
                th = names[tid] = next(
                    (t.name for t in threading.enumerate()
                     if t.ident == tid), f"thread-{tid}")
            if not all_threads and any(th.startswith(p)
                                       for p in _EXCLUDE_THREADS):
                continue
            stack = []
            f = frame
            while f is not None:
                co = f.f_code
                mod = co.co_filename.rsplit("/", 1)[-1]
                stack.append(f"{mod}:{co.co_name}")
                f = f.f_back
            counts[";".join([th] + stack[::-1])] += 1
        time.sleep(interval)
    return "\n".join(f"{k} {v}" for k, v in
                     sorted(counts.items(), key=lambda kv: -kv[1]))


# -- flamegraph rendering ---------------------------------------------------

_PALETTE = ["#e4593b", "#e9743a", "#ec8b3c", "#efa23f", "#f1b843",
            "#d8873b", "#c95f38"]


def _build_trie(folded: str):
    root = {"name": "all", "value": 0, "children": {}}
    for line in folded.splitlines():
        line = line.strip()
        if not line:
            continue
        path, _, cnt = line.rpartition(" ")
        try:
            n = int(cnt)
        except ValueError:
            continue
        root["value"] += n
        node = root
        for part in path.split(";"):
            child = node["children"].get(part)
            if child is None:
                child = node["children"][part] = {
                    "name": part, "value": 0, "children": {}}
            child["value"] += n
            node = child
    return root


def flamegraph_svg(folded: str, width: int = 1200,
                   row_h: int = 16) -> str:
    """Folded stacks → a self-contained SVG flamegraph (hover titles,
    deterministic layout/colors — no JS, no external assets)."""
    root = _build_trie(folded)
    total = max(root["value"], 1)
    rects = []
    depth_max = [0]

    def walk(node, x0: float, depth: int):
        depth_max[0] = max(depth_max[0], depth)
        w = node["value"] / total * width
        if w >= 0.5 and depth >= 0:
            color = _PALETTE[hash(node["name"]) % len(_PALETTE)]
            rects.append((x0, depth, w, node["name"], node["value"],
                          color))
        x = x0
        for child in sorted(node["children"].values(),
                            key=lambda c: -c["value"]):
            walk(child, x, depth + 1)
            x += child["value"] / total * width

    walk(root, 0.0, 0)
    height = (depth_max[0] + 2) * row_h
    out = [f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
           f'height="{height}" font-family="monospace" font-size="11">',
           f'<rect width="{width}" height="{height}" fill="#fffdf7"/>']
    for x, depth, w, name, value, color in rects:
        y = height - (depth + 1) * row_h
        pct = 100.0 * value / total
        label = name if w > 7 * len(name) * 0.9 else (
            name[: max(0, int(w / 7)) - 1] + "…" if w > 20 else "")
        out.append(
            f'<g><title>{_esc(name)} — {value} samples '
            f'({pct:.1f}%)</title>'
            f'<rect x="{x:.1f}" y="{y}" width="{max(w - 0.3, 0.2):.1f}" '
            f'height="{row_h - 1}" fill="{color}" rx="1"/>'
            + (f'<text x="{x + 2:.1f}" y="{y + row_h - 5}" '
               f'fill="#2a1f1a">{_esc(label)}</text>' if label else "")
            + "</g>")
    out.append("</svg>")
    return "\n".join(out)


def _esc(s: str) -> str:
    return (s.replace("&", "&amp;").replace("<", "&lt;")
            .replace(">", "&gt;"))
