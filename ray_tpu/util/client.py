"""Ray Client: thin ``ray://`` proxy for driving a cluster remotely.

Reference capability: python/ray/util/client/ — a client that pickles
API calls to a server-side driver (server/server.py:96 RayletServicer,
proxier multiplexing, ray_client.proto wire surface) so
``ray_tpu.init(address="ray://host:port")`` works from outside the
cluster without running a local node.

Re-derived design: the ClientServer process is itself a normal driver
attached to the cluster; each client connection speaks a small op
vocabulary (connect/export/task/create_actor/actor_task/put/get/wait/
free/release/request) over the same length-prefixed-pickle framing as
the rest of the control plane (core/protocol.py). The server holds one
live server-side ObjectRef per client-held ref in a per-connection
registry, so cluster-side refcounting sees client refs; releases (and
disconnects) drain the registry. Client-created non-detached actors are
killed on disconnect, matching the reference's session cleanup.
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Optional, Sequence

import cloudpickle

from ray_tpu.core.ids import ActorID, ObjectID
from ray_tpu.core.object_ref import ObjectRef, get_tracker
from ray_tpu.core.protocol import Connection, ConnectionClosed

PROTOCOL_VERSION = 1


def _dumps(obj) -> bytes:
    return cloudpickle.dumps(obj)


def _loads(blob: bytes):
    import pickle
    return pickle.loads(blob)


# ========================================================================
# Server
# ========================================================================

class _ClientSession:
    """Per-connection server state: refs held on behalf of the client,
    actors created by it."""

    def __init__(self):
        self.refs: dict[bytes, ObjectRef] = {}
        self.actors: dict[bytes, bool] = {}  # actor_id -> detached


class ClientServer:
    """Accepts ray:// clients and proxies them onto this process's
    runtime (reference: util/client/server/server.py serve +
    proxier.py)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 10001):
        import ray_tpu
        if not ray_tpu.is_initialized():
            raise RuntimeError("ray_tpu.init() the cluster connection "
                               "before starting ClientServer")
        from ray_tpu.core.runtime import get_runtime
        self._rt = get_runtime()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = f"ray://{host}:{self._sock.getsockname()[1]}"
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True,
                                        name="raytpu-client-server")
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                sock, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_one,
                             args=(Connection(sock),), daemon=True).start()

    def _serve_one(self, conn: Connection):
        sess = _ClientSession()
        try:
            while True:
                msg = conn.recv()
                try:
                    reply = self._dispatch(msg, sess)
                except Exception as e:  # noqa: BLE001 - send to client
                    reply = {"error": _dumps(e)}
                if msg.get("no_reply"):
                    continue  # fire-and-forget op: never reply, even on error
                reply["rid"] = msg.get("rid")
                conn.send(reply)
        except ConnectionClosed:
            pass
        finally:
            self._cleanup(sess)

    def _cleanup(self, sess: _ClientSession):
        sess.refs.clear()
        import ray_tpu
        for aid, detached in sess.actors.items():
            if not detached:
                try:
                    self._rt.kill_actor(ActorID(aid))
                except Exception:  # noqa: BLE001
                    pass

    def _register(self, sess, refs):
        out = []
        for r in refs:
            sess.refs[r.binary()] = r
            out.append(r.binary())
        return out

    def _dispatch(self, msg: dict, sess: _ClientSession) -> dict:
        rt = self._rt
        op = msg["op"]
        if op == "connect":
            if msg.get("version") != PROTOCOL_VERSION:
                raise RuntimeError(
                    f"client protocol {msg.get('version')} != server "
                    f"{PROTOCOL_VERSION}")
            return {"config_dict": dict(rt.client.config_dict),
                    "namespace": rt.namespace,
                    "worker_id": rt.client.worker_id}
        if op == "export":
            fn = _loads(msg["blob"])
            return {"fn_id": rt.export_function(fn)}
        if op == "task":
            args, kwargs = _loads(msg["args_blob"])
            res = rt.submit_task(msg["fn_id"], args, kwargs,
                                 **msg["opts"])
            refs = (res if isinstance(res, list)
                    else [] if res is None else [res])
            return {"ref_ids": self._register(sess, refs),
                    "shape": ("list" if isinstance(res, list)
                              else "none" if res is None else "one")}
        if op == "create_actor":
            args, kwargs = _loads(msg["args_blob"])
            aid = rt.create_actor(msg["fn_id"], args, kwargs,
                                  **msg["opts"])
            sess.actors[aid.binary()] = bool(msg.get("detached"))
            return {"actor_id": aid.binary()}
        if op == "actor_task":
            args, kwargs = _loads(msg["args_blob"])
            res = rt.submit_actor_task(
                ActorID(msg["actor_id"]), msg["nonce"], msg["seq"],
                msg["method"], args, kwargs, **msg["opts"])
            refs = (res if isinstance(res, list)
                    else [] if res is None else [res])
            return {"ref_ids": self._register(sess, refs),
                    "shape": ("list" if isinstance(res, list)
                              else "none" if res is None else "one")}
        if op == "kill_actor":
            rt.kill_actor(ActorID(msg["actor_id"]),
                          no_restart=msg["no_restart"])
            sess.actors.pop(msg["actor_id"], None)
            return {}
        if op == "put":
            ref = rt.put(_loads(msg["blob"]))
            return {"ref_id": self._register(sess, [ref])[0]}
        if op == "get":
            refs = [sess.refs.get(b) or ObjectRef(ObjectID(b))
                    for b in msg["ref_ids"]]
            vals = rt.get(refs, timeout=msg.get("timeout"))
            return {"blob": _dumps(vals)}
        if op == "wait":
            id_to_ref = {b: (sess.refs.get(b) or ObjectRef(ObjectID(b)))
                         for b in msg["ref_ids"]}
            ready, rest = rt.wait(
                [id_to_ref[b] for b in msg["ref_ids"]],
                num_returns=msg["num_returns"],
                timeout=msg.get("timeout"))
            return {"ready": [r.binary() for r in ready],
                    "rest": [r.binary() for r in rest]}
        if op == "free":
            refs = [sess.refs.get(b) or ObjectRef(ObjectID(b))
                    for b in msg["ref_ids"]]
            rt.free(refs)
            return {}
        if op == "release":
            for b in msg["ref_ids"]:
                sess.refs.pop(b, None)
            return {}
        if op == "request":  # generic state-API pass-through
            return {"reply": rt.client.request(msg["msg"])}
        raise ValueError(f"unknown client op {op!r}")

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


# ========================================================================
# Client
# ========================================================================

class _ClientShim:
    """Quacks like NodeClient for the bits the API layer touches
    (config_dict, worker_id, request)."""

    def __init__(self, proxy: "ClientRuntime", config_dict: dict,
                 worker_id: str):
        self._proxy = proxy
        self.config_dict = config_dict
        self.worker_id = worker_id

    def request(self, msg: dict, timeout: Optional[float] = None) -> dict:
        return self._proxy._call({"op": "request", "msg": msg})["reply"]


class ClientRuntime:
    """Drop-in Runtime replacement speaking the client protocol
    (reference: util/client/worker.py:81 Worker)."""

    mode = "client"

    def __init__(self, address: str, namespace: str = "default",
                 timeout: float = 30.0):
        hostport = address[len("ray://"):] if address.startswith("ray://") \
            else address
        host, _, port = hostport.rpartition(":")
        sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                        timeout=timeout)
        sock.settimeout(None)
        self._conn = Connection(sock)
        self._lock = threading.Lock()
        self._rid = 0
        hello = self._call({"op": "connect", "version": PROTOCOL_VERSION})
        self.namespace = namespace
        self.client = _ClientShim(self, hello["config_dict"],
                                  "client-of-" + hello["worker_id"])
        self._fn_ids: dict[int, str] = {}
        self.node_service = None
        get_tracker().set_sink(self._release_refs)

    # -- plumbing ----------------------------------------------------------
    def _call(self, msg: dict) -> dict:
        with self._lock:
            self._rid += 1
            msg["rid"] = self._rid
            self._conn.send(msg)
            while True:
                reply = self._conn.recv()
                if reply.get("rid") == msg["rid"]:
                    break
        if "error" in reply:
            raise _loads(reply["error"])
        return reply

    def _refs_from(self, reply) -> Any:
        refs = [ObjectRef(ObjectID(b), owner=self.client.worker_id)
                for b in reply["ref_ids"]]
        shape = reply["shape"]
        if shape == "one":
            return refs[0]
        if shape == "none":
            return None
        return refs

    # -- Runtime surface ---------------------------------------------------
    def export_function(self, fn) -> str:
        import hashlib
        blob = _dumps(fn)
        # key by content hash, not id(fn): CPython reuses addresses
        # after GC, which would silently alias two different functions
        key = hashlib.sha1(blob).hexdigest()
        if key not in self._fn_ids:
            self._fn_ids[key] = self._call(
                {"op": "export", "blob": blob})["fn_id"]
        return self._fn_ids[key]

    def submit_task(self, function_id: str, args, kwargs, **opts):
        return self._refs_from(self._call({
            "op": "task", "fn_id": function_id,
            "args_blob": _dumps((args, kwargs)), "opts": opts}))

    def create_actor(self, function_id: str, args, kwargs, **opts):
        detached = opts.pop("lifetime", None) == "detached" or \
            bool(opts.get("name"))
        reply = self._call({
            "op": "create_actor", "fn_id": function_id,
            "args_blob": _dumps((args, kwargs)), "opts": opts,
            "detached": detached})
        return ActorID(reply["actor_id"])

    def submit_actor_task(self, actor_id: ActorID, caller_nonce: bytes,
                          seq: int, method: str, args, kwargs, **opts):
        return self._refs_from(self._call({
            "op": "actor_task", "actor_id": actor_id.binary(),
            "nonce": caller_nonce, "seq": seq, "method": method,
            "args_blob": _dumps((args, kwargs)), "opts": opts}))

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        self._call({"op": "kill_actor", "actor_id": actor_id.binary(),
                    "no_restart": no_restart})

    def put(self, value) -> ObjectRef:
        reply = self._call({"op": "put", "blob": _dumps(value)})
        return ObjectRef(ObjectID(reply["ref_id"]),
                         owner=self.client.worker_id)

    def get(self, refs: Sequence[ObjectRef], timeout=None) -> list:
        reply = self._call({"op": "get",
                            "ref_ids": [r.binary() for r in refs],
                            "timeout": timeout})
        return _loads(reply["blob"])

    def wait(self, refs, num_returns=1, timeout=None):
        by_id = {r.binary(): r for r in refs}
        reply = self._call({"op": "wait",
                            "ref_ids": [r.binary() for r in refs],
                            "num_returns": num_returns,
                            "timeout": timeout})
        return ([by_id[b] for b in reply["ready"]],
                [by_id[b] for b in reply["rest"]])

    def free(self, refs) -> None:
        self._call({"op": "free",
                    "ref_ids": [r.binary() for r in refs]})

    def as_future(self, ref: ObjectRef):
        import concurrent.futures
        fut: concurrent.futures.Future = concurrent.futures.Future()

        def run():
            try:
                fut.set_result(self.get([ref], timeout=None)[0])
            except Exception as e:  # noqa: BLE001
                fut.set_exception(e)

        threading.Thread(target=run, daemon=True).start()
        return fut

    def _release_refs(self, object_ids: list) -> None:
        # fire-and-forget: this can run from ObjectRef.__del__ during GC
        # while this thread is inside _call holding self._lock — a
        # request/response here would self-deadlock (Connection.send is
        # itself thread-safe, and the server sends no reply for no_reply
        # messages so the rid stream stays in sync)
        try:
            self._conn.send({"op": "release",
                             "ref_ids": list(object_ids),
                             "no_reply": True})
        except Exception:  # noqa: BLE001 - racing disconnect
            pass

    def shutdown(self) -> None:
        get_tracker().set_sink(None)
        try:
            self._conn.sock.close()
        except OSError:
            pass


def connect(address: str, namespace: str = "default") -> ClientRuntime:
    """Connect to a ray:// client server (reference:
    util/client/__init__.py connect)."""
    return ClientRuntime(address, namespace=namespace)
