"""State API: list and summarize live cluster entities.

The analogue of the reference's state observability API
(reference: python/ray/experimental/state/api.py:736,959 — list_tasks /
list_actors / list_objects / list_nodes / list_workers + summarize_*),
served from the node service's state tables (and, in cluster mode, the
head's cluster-scope tables for nodes/actors).
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Optional


def _query(what: str) -> list | dict:
    from ray_tpu.core.runtime import get_runtime
    return get_runtime().client.request({"t": "state", "what": what})["data"]


def list_tasks(filters: Optional[list] = None) -> list[dict]:
    """Tasks submitted through this node: id, name, state, error,
    timing.  filters: [(key, "=", value), ...] subset."""
    return _apply_filters(_query("tasks"), filters)


def list_actors(filters: Optional[list] = None) -> list[dict]:
    """Actors known cluster-wide (head) or on this node (standalone)."""
    data = _query("cluster_actors")
    if not data:   # standalone node: local table
        data = _query("actors")
    return _apply_filters(data, filters)


def list_objects(filters: Optional[list] = None) -> list[dict]:
    """Objects resident on this node: id, state, location, size."""
    return _apply_filters(_query("objects"), filters)


def list_workers(filters: Optional[list] = None) -> list[dict]:
    return _apply_filters(_query("workers"), filters)


def list_nodes(filters: Optional[list] = None) -> list[dict]:
    return _apply_filters(_query("nodes"), filters)


def list_task_events() -> list[dict]:
    """Raw task state-transition events (the timeline's source)."""
    return _query("task_events")


def _apply_filters(data: list, filters: Optional[list]) -> list:
    if not filters:
        return data
    out = []
    for row in data:
        ok = True
        for key, op, value in filters:
            have = row.get(key)
            if op in ("=", "=="):
                ok = have == value
            elif op == "!=":
                ok = have != value
            else:
                raise ValueError(f"unsupported filter op {op!r}")
            if not ok:
                break
        if ok:
            out.append(row)
    return out


def group_counts(rows: list[dict], key: str) -> dict:
    """Group rows by `key`, counting states — the shared shape of every
    summarize_* view (and of the CLI summary command)."""
    groups: dict[str, Counter] = defaultdict(Counter)
    for row in rows:
        groups[row.get(key) or "<anonymous>"][row.get("state", "?")] += 1
    return {"cluster": {name: dict(states)
                        for name, states in sorted(groups.items())},
            "total": sum(sum(c.values()) for c in groups.values())}


def summarize_tasks() -> dict:
    """Per-function-name counts by state (reference:
    state/api.py summarize_tasks)."""
    return group_counts(list_tasks(), "name")


def summarize_actors() -> dict:
    return group_counts(list_actors(), "class_name")


def summarize_objects() -> dict:
    by_loc: Counter = Counter()
    total_bytes = 0
    for o in list_objects():
        by_loc[o.get("loc") or o["state"]] += 1
        total_bytes += o.get("size") or 0
    return {"by_location": dict(by_loc), "total_bytes": total_bytes,
            "total": sum(by_loc.values())}


def events_to_trace(events: list[dict]) -> list[dict]:
    """Pair RUNNING -> FINISHED/FAILED task events into chrome-trace 'X'
    complete events (reference: _private/profiling.py chrome format)."""
    start: dict[str, dict] = {}
    trace: list[dict] = []
    for ev in events:
        tid = ev["task_id"]
        if ev["state"] == "RUNNING":
            start[tid] = ev
        elif ev["state"] in ("FINISHED", "FAILED") and tid in start:
            s = start.pop(tid)
            trace.append({
                "name": ev.get("name") or tid[:8],
                "cat": "task",
                "ph": "X",
                "ts": s["time"] * 1e6,
                "dur": max(0.0, (ev["time"] - s["time"]) * 1e6),
                "pid": "ray_tpu",
                "tid": s.get("worker") or 0,
                "args": {"task_id": tid,
                         "state": ev["state"]},
            })
    return trace


def timeline(filename: Optional[str] = None) -> list[dict]:
    """Chrome-trace-format task timeline (reference: ray.timeline,
    state/api.py timeline).  Returns the trace; writes JSON if
    filename."""
    import json

    trace = events_to_trace(list_task_events())
    if filename:
        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
