"""Merged Chrome/Perfetto trace export.

One ``trace_event``-format JSON from every observability source the
framework has (reference: ``ray.timeline()`` Chrome-trace export,
python/ray/experimental/state + _private/profiling.py):

  * task state events        → ``X`` slices (RUNNING→FINISHED pairs)
  * flight-recorder records  → one ``X`` slice PER LIFECYCLE STAGE, so
    "where do the milliseconds go" is visible per task
  * tracing spans            → ``X`` slices grouped by emitting pid
  * chaos (fault-injection)  → ``i`` instant events, so injected faults
    show up attributed in the same view as the latency they caused
  * serve-fleet ingress      → admission/shed/route/resume/scale events
    (serve/fleet): queued admissions render as ``X`` slices (the queue
    wait is visible time), everything else as ``i`` instants, one track
    per event kind; drain begin/settle pairs and cluster-prefix
    adoption begin/complete/fallback pairs merge into single ``X``
    slices so their durations read straight off the trace
  * inference-engine request slices → one ``X`` per completed request
    (pid "engine", tid = engine name) spanning submit→finish, with
    speculative-decoding accept/reject counts — and, for meshed
    engines, the serving geometry (mesh_devices / tp_shards) — merged
    into the slice args (engine_request events from
    InferenceEngine._fr_note)

Output loads in chrome://tracing and ui.perfetto.dev (both accept the
``{"traceEvents": [...]}`` object form and string pid/tid values).
"""

from __future__ import annotations

from typing import Iterable


def build_trace(task_events: Iterable = (), records: Iterable = (),
                spans: Iterable = (), faults: Iterable = (),
                ingress: Iterable = ()) -> dict:
    """Merge all sources into one Perfetto-loadable trace dict."""
    from ray_tpu.util.state import events_to_trace

    ev: list = list(events_to_trace(list(task_events)))

    for r in records:
        # r: flight-recorder export — {"task_id", "name", "worker",
        # "start_ts", "stages": [(stage, wall_ts), ...]}
        stages = r.get("stages") or []
        # tid must be unique per task: concurrent tasks of one function
        # would otherwise collapse onto a single track and interleave as
        # bogus nesting exactly when there IS concurrency to look at
        tid = f"{r.get('name') or '?'} {r.get('task_id', '?')[:8]}"
        prev_ts = None
        for stage, ts in stages:
            if prev_ts is not None:
                ev.append({
                    "name": stage, "cat": "lifecycle", "ph": "X",
                    "ts": prev_ts * 1e6,
                    "dur": max(0.0, (ts - prev_ts) * 1e6),
                    "pid": "lifecycle", "tid": tid,
                    "args": {"task_id": r.get("task_id"),
                             "worker": r.get("worker")},
                })
            prev_ts = ts

    for s in spans:
        if "start" not in s or "end" not in s:
            continue
        ev.append({
            "name": s.get("name", "span"), "cat": "span", "ph": "X",
            "ts": s["start"] * 1e6,
            "dur": max(0.0, (s["end"] - s["start"]) * 1e6),
            "pid": f"pid {s.get('pid', '?')}",
            "tid": s.get("kind", "span"),
            "args": {"trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "status": s.get("status")},
        })

    for f in faults:
        ev.append({
            "name": f"chaos:{f.get('point')}:{f.get('action')}",
            "cat": "chaos", "ph": "i", "s": "g",
            "ts": float(f.get("t", 0.0)) * 1e6,
            "pid": "chaos", "tid": f.get("point", "?"),
            "args": {"detail": f.get("detail")},
        })

    # drain lifecycle pairing: a drain_begin and its settling
    # drain_complete / drain_timeout (same replica) render as ONE slice
    # so the drain DURATION is visible time; unpaired events fall back
    # to instants below
    drain_open: dict = {}    # replica tag -> begin event
    # prefix-adoption pairing: an adopt_begin and its settling
    # adopt_complete / adopt_fallback (same adopt id) render as ONE
    # slice — the remote fetch+install cost is visible time, and a
    # fallback slice carries the failure reason in args
    adopt_open: dict = {}    # adopt id -> begin event
    for g in ingress:
        # g: fleet ingress event — {"t", "kind", "deployment", ...}
        # (serve/fleet/ingress.py Fleet.note); an admit that waited in
        # the admission queue becomes a slice ENDING at the admit stamp
        # so the queueing delay is visible time, everything else an
        # instant on its kind's track
        kind = g.get("kind", "?")
        ts = float(g.get("t", 0.0)) * 1e6
        args = {k: v for k, v in g.items() if k not in ("t", "kind")}
        queued = float(g.get("queued_s") or 0.0)
        if kind == "engine_request":
            # inference-engine request slice (engine._fr_note): one X
            # per completed request on the engine's own track, carrying
            # speculative accept/reject counts in args so "why was this
            # stream fast/slow" reads straight off the trace
            t0 = float(g.get("start_t", g.get("t", 0.0))) * 1e6
            ev.append({
                "name": f"engine:{g.get('req', '?')}",
                "cat": "engine", "ph": "X",
                "ts": t0, "dur": max(0.0, ts - t0),
                "pid": "engine", "tid": g.get("engine", "?"),
                "args": args,
            })
            continue
        if kind == "admit" and queued > 0:
            ev.append({
                "name": "ingress:queued", "cat": "ingress", "ph": "X",
                "ts": ts - queued * 1e6, "dur": queued * 1e6,
                "pid": "ingress", "tid": "admit", "args": args,
            })
            continue
        if kind == "adopt_begin" and g.get("adopt") is not None:
            adopt_open[g["adopt"]] = g
            continue
        if kind in ("adopt_complete", "adopt_fallback") \
                and g.get("adopt") in adopt_open:
            begin = adopt_open.pop(g["adopt"])
            t0 = float(begin.get("t", 0.0)) * 1e6
            args["outcome"] = kind
            args.setdefault("holder", begin.get("holder"))
            args.setdefault("replica", begin.get("replica"))
            args.setdefault("tokens", begin.get("tokens"))
            ev.append({
                "name": f"ingress:adopt:{begin.get('holder', '?')}"
                        f"->{begin.get('replica', '?')}",
                "cat": "ingress", "ph": "X",
                "ts": t0, "dur": max(0.0, ts - t0),
                "pid": "ingress", "tid": "adopt", "args": args,
            })
            continue
        if kind == "drain_begin" and g.get("replica") is not None:
            drain_open[g["replica"]] = g
            continue
        if kind in ("drain_complete", "drain_timeout") \
                and g.get("replica") in drain_open:
            begin = drain_open.pop(g["replica"])
            t0 = float(begin.get("t", 0.0)) * 1e6
            args["outcome"] = kind
            args["reason"] = begin.get("reason")
            ev.append({
                "name": f"ingress:drain:{g['replica']}",
                "cat": "ingress", "ph": "X",
                "ts": t0, "dur": max(0.0, ts - t0),
                "pid": "ingress", "tid": "drain", "args": args,
            })
            continue
        ev.append({
            "name": f"ingress:{kind}", "cat": "ingress", "ph": "i",
            "s": "g", "ts": ts, "pid": "ingress", "tid": kind,
            "args": args,
        })
    for tag, begin in drain_open.items():
        # drain still in progress at export time: show the begin
        ev.append({
            "name": "ingress:drain_begin", "cat": "ingress", "ph": "i",
            "s": "g", "ts": float(begin.get("t", 0.0)) * 1e6,
            "pid": "ingress", "tid": "drain",
            "args": {k: v for k, v in begin.items()
                     if k not in ("t", "kind")},
        })
    for aid, begin in adopt_open.items():
        # adoption still in flight (or its settle event was evicted):
        # show the begin rather than dropping it
        ev.append({
            "name": "ingress:adopt_begin", "cat": "ingress", "ph": "i",
            "s": "g", "ts": float(begin.get("t", 0.0)) * 1e6,
            "pid": "ingress", "tid": "adopt",
            "args": {k: v for k, v in begin.items()
                     if k not in ("t", "kind")},
        })

    ev.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": ev, "displayTimeUnit": "ms"}
