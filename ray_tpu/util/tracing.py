"""Distributed tracing: spans around task/actor submission + execution.

Reference capability: python/ray/util/tracing/tracing_helper.py — when
tracing is enabled, every ``.remote()`` call opens a client span whose
context is injected into the task spec, and the executing worker opens
a server span as its child, so cross-process traces stitch together in
one trace id.

Dependency-light redesign (no opentelemetry wheel in this image): spans
are plain dicts with W3C-style ids (128-bit trace id, 64-bit span id);
context propagates in-process via a contextvar and cross-process inside
the task spec (``trace_ctx``).  Finished spans land in an in-process
buffer and, when ``RAY_TPU_TRACE_DIR`` is set, one JSONL file per
process — ``collect_spans()`` merges them for analysis/tests.

Emission is batched: ``_emit`` appends to a pending list under the
span-buffer lock and the actual ``write+flush`` runs under a separate
I/O lock, draining everything pending in one write.  Threads that find
the I/O lock busy just leave their span pending for the current writer
— the hot path never blocks on disk (the previous design held the one
global lock across ``write``+``flush`` per span, serializing every
tracer behind the disk).  ``flush_spans()`` (also run at exit and by
``collect_spans``) force-drains.
"""

from __future__ import annotations

import atexit
import contextlib
import contextvars
import glob
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

_current: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)

_lock = threading.Lock()          # span buffer + pending list
_io_lock = threading.Lock()       # file open/write/flush
_finished: List[dict] = []
_pending: List[dict] = []         # spans awaiting a file write
_MAX_BUFFER = 10_000
_file = None
_file_dir: Optional[str] = None   # dir _file was opened in (reset on change)
_enabled: Optional[bool] = None


def tracing_enabled() -> bool:
    """Flag gate (reference: tracing enabled via ray.init tracing
    startup hook / RAY_TRACING_ENABLED)."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_TRACING", "").lower() in (
            "1", "true", "yes") or bool(os.environ.get("RAY_TPU_TRACE_DIR"))
    return _enabled


def enable_tracing(trace_dir: Optional[str] = None) -> None:
    global _enabled
    flush_spans()   # leftover pending spans belong to the PREVIOUS dir
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    if trace_dir:
        # the drain notices the dir change and re-points the cached file
        os.environ["RAY_TPU_TRACE_DIR"] = trace_dir


def disable_tracing() -> None:
    global _enabled, _file, _file_dir
    flush_spans()
    _enabled = False
    os.environ.pop("RAY_TPU_TRACING", None)
    os.environ.pop("RAY_TPU_TRACE_DIR", None)
    with _io_lock:
        if _file is not None:
            _file.close()
            _file = None
            _file_dir = None


def _emit(span: dict) -> None:
    with _lock:
        _finished.append(span)
        if len(_finished) > _MAX_BUFFER:
            del _finished[:len(_finished) - _MAX_BUFFER]
        if not os.environ.get("RAY_TPU_TRACE_DIR"):
            return
        _pending.append(span)
    # opportunistic drain: whoever gets the I/O lock writes the whole
    # batch; a contended emitter's span is picked up by a retry here —
    # the in-flight writer popped its batch BEFORE this append landed,
    # so someone must come back for it or it sits undurable
    while True:
        if not _io_lock.acquire(blocking=False):
            return   # the current writer re-checks after its drain
        try:
            _drain_locked()
        finally:
            _io_lock.release()
        with _lock:
            if not _pending:
                return


def flush_spans() -> None:
    """Force-drain pending spans to the trace file (blocking)."""
    with _io_lock:
        _drain_locked()


atexit.register(flush_spans)


def _drain_locked() -> None:
    """Write+flush everything pending.  Caller holds _io_lock."""
    global _file, _file_dir
    with _lock:
        if not _pending:
            return
        batch, _pending[:] = list(_pending), []
    d = os.environ.get("RAY_TPU_TRACE_DIR")
    if not d:
        return
    if _file is None or _file_dir != d:
        # dir changed between disable/enable cycles: re-point the file
        if _file is not None:
            try:
                _file.close()
            except OSError:
                pass
        os.makedirs(d, exist_ok=True)
        _file = open(os.path.join(d, f"spans-{os.getpid()}.jsonl"), "a")
        _file_dir = d
    _file.write("".join(json.dumps(s) + "\n" for s in batch))
    _file.flush()


@contextlib.contextmanager
def start_span(name: str, kind: str = "internal",
               attributes: Optional[Dict[str, Any]] = None,
               remote_ctx: Optional[dict] = None) -> Iterator[dict]:
    """Open a span; parent = remote_ctx (cross-process) or the current
    in-process span. Yields the mutable span dict (add attributes)."""
    if not tracing_enabled():
        yield {}
        return
    parent = remote_ctx if remote_ctx is not None else _current.get()
    span = {
        "name": name,
        "kind": kind,
        "trace_id": (parent or {}).get("trace_id") or secrets.token_hex(16),
        "span_id": secrets.token_hex(8),
        "parent_id": (parent or {}).get("span_id"),
        "start": time.time(),
        "pid": os.getpid(),
        "attributes": dict(attributes or {}),
        "status": "ok",
    }
    token = _current.set({"trace_id": span["trace_id"],
                          "span_id": span["span_id"]})
    try:
        yield span
    except BaseException as e:
        span["status"] = f"error: {type(e).__name__}"
        raise
    finally:
        _current.reset(token)
        span["end"] = time.time()
        _emit(span)


def inject_context() -> Optional[dict]:
    """Current span context for embedding in a task spec (reference:
    tracing_helper.py _inject_tracing_into_function)."""
    if not tracing_enabled():
        return None
    return _current.get()


def get_finished_spans(name: Optional[str] = None) -> List[dict]:
    with _lock:
        spans = list(_finished)
    if name:
        spans = [s for s in spans if s["name"] == name]
    return spans


def clear() -> None:
    with _lock:
        _finished.clear()
        _pending.clear()


def collect_spans(trace_dir: Optional[str] = None) -> List[dict]:
    """Merge every process's span file (worker spans included).  A
    truncated trailing line (a writer crashed or was killed mid-write)
    is skipped instead of poisoning the whole collection."""
    flush_spans()   # this process's pending spans must be readable too
    d = trace_dir or os.environ.get("RAY_TPU_TRACE_DIR")
    if not d:
        return get_finished_spans()
    out = []
    for p in sorted(glob.glob(os.path.join(d, "spans-*.jsonl"))):
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue   # truncated/garbled line: skip, keep rest
    return out
