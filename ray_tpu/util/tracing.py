"""Distributed tracing: spans around task/actor submission + execution.

Reference capability: python/ray/util/tracing/tracing_helper.py — when
tracing is enabled, every ``.remote()`` call opens a client span whose
context is injected into the task spec, and the executing worker opens
a server span as its child, so cross-process traces stitch together in
one trace id.

Dependency-light redesign (no opentelemetry wheel in this image): spans
are plain dicts with W3C-style ids (128-bit trace id, 64-bit span id);
context propagates in-process via a contextvar and cross-process inside
the task spec (``trace_ctx``). Finished spans land in an in-process
buffer and, when ``RAY_TPU_TRACE_DIR`` is set, one JSONL file per
process — ``collect_spans()`` merges them for analysis/tests.
"""

from __future__ import annotations

import contextlib
import contextvars
import glob
import json
import os
import secrets
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

_current: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "ray_tpu_trace_ctx", default=None)

_lock = threading.Lock()
_finished: List[dict] = []
_MAX_BUFFER = 10_000
_file = None
_enabled: Optional[bool] = None


def tracing_enabled() -> bool:
    """Flag gate (reference: tracing enabled via ray.init tracing
    startup hook / RAY_TRACING_ENABLED)."""
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RAY_TPU_TRACING", "").lower() in (
            "1", "true", "yes") or bool(os.environ.get("RAY_TPU_TRACE_DIR"))
    return _enabled


def enable_tracing(trace_dir: Optional[str] = None) -> None:
    global _enabled
    _enabled = True
    os.environ["RAY_TPU_TRACING"] = "1"
    if trace_dir:
        os.environ["RAY_TPU_TRACE_DIR"] = trace_dir


def disable_tracing() -> None:
    global _enabled, _file
    _enabled = False
    os.environ.pop("RAY_TPU_TRACING", None)
    os.environ.pop("RAY_TPU_TRACE_DIR", None)
    with _lock:
        if _file is not None:
            _file.close()
            _file = None


def _emit(span: dict) -> None:
    global _file
    with _lock:
        _finished.append(span)
        if len(_finished) > _MAX_BUFFER:
            del _finished[:len(_finished) - _MAX_BUFFER]
        d = os.environ.get("RAY_TPU_TRACE_DIR")
        if d:
            if _file is None:
                os.makedirs(d, exist_ok=True)
                _file = open(os.path.join(
                    d, f"spans-{os.getpid()}.jsonl"), "a")
            _file.write(json.dumps(span) + "\n")
            _file.flush()


@contextlib.contextmanager
def start_span(name: str, kind: str = "internal",
               attributes: Optional[Dict[str, Any]] = None,
               remote_ctx: Optional[dict] = None) -> Iterator[dict]:
    """Open a span; parent = remote_ctx (cross-process) or the current
    in-process span. Yields the mutable span dict (add attributes)."""
    if not tracing_enabled():
        yield {}
        return
    parent = remote_ctx if remote_ctx is not None else _current.get()
    span = {
        "name": name,
        "kind": kind,
        "trace_id": (parent or {}).get("trace_id") or secrets.token_hex(16),
        "span_id": secrets.token_hex(8),
        "parent_id": (parent or {}).get("span_id"),
        "start": time.time(),
        "pid": os.getpid(),
        "attributes": dict(attributes or {}),
        "status": "ok",
    }
    token = _current.set({"trace_id": span["trace_id"],
                          "span_id": span["span_id"]})
    try:
        yield span
    except BaseException as e:
        span["status"] = f"error: {type(e).__name__}"
        raise
    finally:
        _current.reset(token)
        span["end"] = time.time()
        _emit(span)


def inject_context() -> Optional[dict]:
    """Current span context for embedding in a task spec (reference:
    tracing_helper.py _inject_tracing_into_function)."""
    if not tracing_enabled():
        return None
    return _current.get()


def get_finished_spans(name: Optional[str] = None) -> List[dict]:
    with _lock:
        spans = list(_finished)
    if name:
        spans = [s for s in spans if s["name"] == name]
    return spans


def clear() -> None:
    with _lock:
        _finished.clear()


def collect_spans(trace_dir: Optional[str] = None) -> List[dict]:
    """Merge every process's span file (worker spans included)."""
    d = trace_dir or os.environ.get("RAY_TPU_TRACE_DIR")
    if not d:
        return get_finished_spans()
    out = []
    for p in sorted(glob.glob(os.path.join(d, "spans-*.jsonl"))):
        with open(p) as f:
            for line in f:
                if line.strip():
                    out.append(json.loads(line))
    return out
