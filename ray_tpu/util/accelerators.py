"""Accelerator type constants for scheduling constraints.

Reference capability: python/ray/util/accelerators/accelerators.py —
string constants users pass as ``accelerator_type=`` so tasks land on
nodes with that hardware. The reference ships GPU types only (**no
TPU** — SURVEY.md §2.4 flags this); the TPU generations are the
first-class citizens here, with the reference's GPU names kept for
migration compatibility.

The constant doubles as a custom-resource name: the autoscaler's TPU
pod provider advertises ``accelerator_type:<TYPE>`` on matching nodes,
and ``@remote(resources={accelerator_resource(TPU_V5E): 1})`` pins
placement.
"""

# TPU generations (the native citizens)
TPU_V2 = "TPU-V2"
TPU_V3 = "TPU-V3"
TPU_V4 = "TPU-V4"
TPU_V5E = "TPU-V5E"      # a.k.a. v5 lite
TPU_V5P = "TPU-V5P"
TPU_V6E = "TPU-V6E"      # Trillium

# reference GPU names kept for migration compatibility
NVIDIA_TESLA_V100 = "V100"
NVIDIA_TESLA_P100 = "P100"
NVIDIA_TESLA_T4 = "T4"
NVIDIA_TESLA_P4 = "P4"
NVIDIA_TESLA_K80 = "K80"
NVIDIA_TESLA_A10G = "A10G"
NVIDIA_TESLA_A100 = "A100"
NVIDIA_H100 = "H100"
AMD_INSTINCT_MI100 = "AMD-Instinct-MI100"
INTEL_MAX_1550 = "Intel-GPU-Max-1550"

_ALL = {v for k, v in list(globals().items())
        if k.isupper() and isinstance(v, str)}


def accelerator_resource(accelerator_type: str) -> str:
    """Custom-resource name a node advertises for this accelerator."""
    return f"accelerator_type:{accelerator_type}"


def is_known_accelerator(accelerator_type: str) -> bool:
    return accelerator_type in _ALL


def detect_tpu_type() -> str:
    """Best-effort TPU generation of the locally visible chip
    (device_kind → constant; None-safe on CPU-only hosts)."""
    try:
        import jax
        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 - no backend
        return ""
    for key, const in (("v5 lite", TPU_V5E), ("v5e", TPU_V5E),
                       ("v5p", TPU_V5P), ("v6", TPU_V6E),
                       ("v4", TPU_V4), ("v3", TPU_V3), ("v2", TPU_V2)):
        if key in kind:
            return const
    return ""
