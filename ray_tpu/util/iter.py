"""ParallelIterator: lazy sharded iterators over actors.

Reference capability: python/ray/util/iter.py — `from_items/from_range/
from_iterators` build a ParallelIterator of N shards, each hosted by a
worker actor; transformations (`for_each`, `filter`, `batch`, ...) are
recorded lazily and applied inside the shard actors; `gather_sync` /
`gather_async` pull elements back to the driver as a LocalIterator.

Re-derived design: shards hold a generator factory plus an op list; a
`_NEXT_BATCH` pull protocol with a sentinel end-marker avoids raising
StopIteration across the RPC boundary.
"""

from __future__ import annotations

import collections
import random
from typing import Any, Callable, Iterable, List, Optional

_END = "__parallel_iter_end__"


def _build_gen(factory, ops, repeat):
    """Materialize a shard's element stream: factory() -> iterable, then
    apply recorded ops in order. Ops: (kind, payload)."""
    def base():
        while True:
            for x in factory():
                yield x
            if not repeat:
                return

    gen = base()
    for kind, arg in ops:
        gen = _apply_op(gen, kind, arg)
    return gen


def _apply_op(gen, kind, arg):
    if kind == "for_each":
        return (arg(x) for x in gen)
    if kind == "filter":
        return (x for x in gen if arg(x))
    if kind == "batch":
        def batched(g=gen, n=arg):
            buf = []
            for x in g:
                buf.append(x)
                if len(buf) == n:
                    yield buf
                    buf = []
            if buf:
                yield buf
        return batched()
    if kind == "flatten":
        return (y for x in gen for y in x)
    if kind == "combine":
        return (y for x in gen for y in arg(x))
    if kind == "shuffle":
        def shuffled(g=gen, size=arg[0], seed=arg[1]):
            rng = random.Random(seed)
            buf = []
            for x in g:
                buf.append(x)
                if len(buf) >= size:
                    i = rng.randrange(len(buf))
                    buf[i], buf[-1] = buf[-1], buf[i]
                    yield buf.pop()
            rng.shuffle(buf)
            yield from buf
        return shuffled()
    raise ValueError(f"unknown op {kind}")


class ParallelIterator:
    """A sharded, lazily transformed iterator (reference:
    python/ray/util/iter.py ParallelIterator)."""

    def __init__(self, factories: List[Callable[[], Iterable]],
                 ops: Optional[list] = None, repeat: bool = False,
                 name: str = "ParallelIterator"):
        self._factories = factories
        self._ops = ops or []
        self._repeat = repeat
        self.name = name

    def __repr__(self):
        return f"{self.name}[shards={self.num_shards()}, ops={len(self._ops)}]"

    def num_shards(self) -> int:
        return len(self._factories)

    # -- lazy transforms ---------------------------------------------------
    def _with(self, kind, arg, label):
        return ParallelIterator(self._factories, self._ops + [(kind, arg)],
                                self._repeat, f"{self.name}.{label}")

    def for_each(self, fn: Callable) -> "ParallelIterator":
        return self._with("for_each", fn, "for_each()")

    def filter(self, fn: Callable) -> "ParallelIterator":
        return self._with("filter", fn, "filter()")

    def batch(self, n: int) -> "ParallelIterator":
        return self._with("batch", n, f"batch({n})")

    def flatten(self) -> "ParallelIterator":
        return self._with("flatten", None, "flatten()")

    def combine(self, fn: Callable) -> "ParallelIterator":
        """fn(item) -> iterable; flat-maps each element."""
        return self._with("combine", fn, "combine()")

    def local_shuffle(self, shuffle_buffer_size: int,
                      seed: Optional[int] = None) -> "ParallelIterator":
        return self._with("shuffle", (shuffle_buffer_size, seed),
                          "local_shuffle()")

    def repartition(self, num_partitions: int) -> "ParallelIterator":
        """Redistribute elements round-robin into num_partitions shards.

        Materializes through the driver (reference repartitions through an
        all-to-all of shard actors; at this scale a driver pass is the
        simpler equivalent since elements already flow through gather)."""
        items = list(self.gather_sync())
        parts = [items[i::num_partitions] for i in range(num_partitions)]
        return ParallelIterator(
            [(lambda p=p: iter(p)) for p in parts],
            name=f"{self.name}.repartition({num_partitions})")

    def union(self, other: "ParallelIterator") -> "ParallelIterator":
        if self._ops or other._ops or self._repeat != other._repeat:
            # fold pending ops into the factories before unioning
            left = self._materialized_factories()
            right = other._materialized_factories()
        else:
            left, right = self._factories, other._factories
        return ParallelIterator(left + right, repeat=False,
                                name=f"{self.name}.union()")

    def _materialized_factories(self):
        facts = []
        for f in self._factories:
            items = list(_build_gen(f, self._ops, self._repeat))
            facts.append(lambda it=items: iter(it))
        return facts

    # -- execution ---------------------------------------------------------
    def _make_actors(self):
        import ray_tpu

        @ray_tpu.remote
        class _ShardActor:
            def __init__(self, factory, ops, repeat):
                self._gen = _build_gen(factory, ops, repeat)

            def next_batch(self, n):
                out = []
                for _ in range(n):
                    try:
                        out.append(next(self._gen))
                    except StopIteration:
                        return out, True
                return out, False

        return [_ShardActor.remote(f, self._ops, self._repeat)
                for f in self._factories]

    def gather_sync(self, batch_ms_hint: int = 16) -> "LocalIterator":
        """Round-robin pull across shards, strict shard order."""
        def gen():
            import ray_tpu
            actors = self._make_actors()
            live = collections.deque((a, False) for a in actors)
            try:
                while live:
                    actor, _ = live.popleft()
                    items, done = ray_tpu.get(
                        actor.next_batch.remote(batch_ms_hint))
                    yield from items
                    if not done:
                        live.append((actor, False))
                    else:
                        ray_tpu.kill(actor)
            finally:
                for a, _ in live:
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass
        return LocalIterator(gen, name=f"{self.name}.gather_sync()")

    def gather_async(self, num_async: int = 1,
                     batch_size: int = 16) -> "LocalIterator":
        """Completion-order pull with num_async in-flight pulls/shard."""
        def gen():
            import ray_tpu
            actors = self._make_actors()
            inflight = {}
            for a in actors:
                for _ in range(num_async):
                    inflight[a.next_batch.remote(batch_size)] = a
            try:
                while inflight:
                    ready, _ = ray_tpu.wait(list(inflight), num_returns=1)
                    ref = ready[0]
                    actor = inflight.pop(ref)
                    items, done = ray_tpu.get(ref)
                    yield from items
                    if not done:
                        inflight[actor.next_batch.remote(batch_size)] = actor
            finally:
                for a in set(inflight.values()) | set(actors):
                    try:
                        ray_tpu.kill(a)
                    except Exception:  # noqa: BLE001
                        pass
        return LocalIterator(gen, name=f"{self.name}.gather_async()")

    def take(self, n: int) -> list:
        return self.gather_sync().take(n)

    def show(self, n: int = 20) -> None:
        for x in self.take(n):
            print(x)

    def shards(self) -> List["LocalIterator"]:
        """One LocalIterator per shard, each running locally (no actors)."""
        return [LocalIterator(
                    lambda f=f: _build_gen(f, self._ops, self._repeat),
                    name=f"{self.name}.shard[{i}]")
                for i, f in enumerate(self._factories)]


class LocalIterator:
    """Driver-local lazy iterator with the same transform surface
    (reference: python/ray/util/iter.py LocalIterator)."""

    def __init__(self, gen_factory: Callable[[], Iterable],
                 ops: Optional[list] = None, name: str = "LocalIterator"):
        self._factory = gen_factory
        self._ops = ops or []
        self.name = name

    def __iter__(self):
        gen = iter(self._factory())
        for kind, arg in self._ops:
            gen = _apply_op(gen, kind, arg)
        return gen

    def _with(self, kind, arg, label):
        return LocalIterator(self._factory, self._ops + [(kind, arg)],
                             f"{self.name}.{label}")

    def for_each(self, fn):
        return self._with("for_each", fn, "for_each()")

    def filter(self, fn):
        return self._with("filter", fn, "filter()")

    def batch(self, n):
        return self._with("batch", n, f"batch({n})")

    def flatten(self):
        return self._with("flatten", None, "flatten()")

    def combine(self, fn):
        return self._with("combine", fn, "combine()")

    def local_shuffle(self, shuffle_buffer_size, seed=None):
        return self._with("shuffle", (shuffle_buffer_size, seed),
                          "local_shuffle()")

    def take(self, n: int) -> list:
        out = []
        for x in self:
            out.append(x)
            if len(out) >= n:
                break
        return out

    def union(self, other: "LocalIterator") -> "LocalIterator":
        left, right = self, other

        def gen():
            yield from left
            yield from right
        return LocalIterator(gen, name=f"{self.name}.union()")


# -- constructors ----------------------------------------------------------
def from_items(items: List[Any], num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    shards = [items[i::num_shards] for i in range(num_shards)]
    return ParallelIterator([(lambda s=s: iter(s)) for s in shards],
                            repeat=repeat,
                            name=f"from_items[{len(items)}]")


def from_range(n: int, num_shards: int = 2,
               repeat: bool = False) -> ParallelIterator:
    bounds = [(i * n // num_shards, (i + 1) * n // num_shards)
              for i in range(num_shards)]
    return ParallelIterator([(lambda b=b: iter(range(*b))) for b in bounds],
                            repeat=repeat, name=f"from_range[{n}]")


def from_iterators(generators: List[Callable[[], Iterable]],
                   repeat: bool = False) -> ParallelIterator:
    """Each element is a zero-arg callable returning an iterable."""
    return ParallelIterator(list(generators), repeat=repeat,
                            name=f"from_iterators[{len(generators)}]")
