"""Distributed Queue (reference capability: python/ray/util/queue.py —
an actor-backed FIFO shared between tasks/actors)."""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.maxsize = maxsize
        self.q: deque = deque()

    def put(self, item) -> bool:
        if self.maxsize > 0 and len(self.q) >= self.maxsize:
            return False
        self.q.append(item)
        return True

    def get(self):
        if not self.q:
            return False, None
        return True, self.q.popleft()

    def qsize(self) -> int:
        return len(self.q)

    def empty(self) -> bool:
        return not self.q


class Queue:
    """Client; the state lives in a named actor so every process sees the
    same queue."""

    def __init__(self, maxsize: int = 0, *, name: Optional[str] = None):
        import ray_tpu
        self._rt = ray_tpu
        opts = {"name": name, "get_if_exists": True} if name else {}
        Act = ray_tpu.remote(_QueueActor)
        if opts:
            Act = Act.options(**opts)
        self._actor = Act.remote(maxsize)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            ok = self._rt.get(self._actor.put.remote(item), timeout=60)
            if ok:
                return
            if not block or (deadline and time.time() > deadline):
                raise Full()
            time.sleep(0.01)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        deadline = None if timeout is None else time.time() + timeout
        while True:
            ok, item = self._rt.get(self._actor.get.remote(), timeout=60)
            if ok:
                return item
            if not block or (deadline and time.time() > deadline):
                raise Empty()
            time.sleep(0.01)

    def put_nowait(self, item):
        return self.put(item, block=False)

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        return self._rt.get(self._actor.qsize.remote(), timeout=60)

    def empty(self) -> bool:
        return self._rt.get(self._actor.empty.remote(), timeout=60)
