"""multiprocessing.Pool API over ray_tpu tasks.

Reference capability: python/ray/util/multiprocessing/pool.py — a drop-in
`Pool` whose workers are cluster actors, so `pool.map` fans out across
the cluster instead of local forks. Re-derived for ray_tpu: each pool
worker is an actor holding an optional initializer state; chunked
submission mirrors stdlib `multiprocessing.pool.Pool` semantics
(chunksize, ordered map vs imap_unordered, AsyncResult).
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence


class TimeoutError(Exception):
    """Raised when AsyncResult.get times out (mirrors mp.TimeoutError)."""


class AsyncResult:
    """Handle to an in-flight map/apply (mirrors mp.pool.AsyncResult)."""

    def __init__(self, refs: list, single: bool, pool: "Pool",
                 callback=None, error_callback=None):
        self._refs = refs
        self._single = single
        self._pool = pool
        self._callback = callback
        self._error_callback = error_callback
        self._result = None
        self._error = None
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._bg = None
        if callback is not None or error_callback is not None:
            # stdlib mp.Pool fires callbacks from its result-handler
            # thread as soon as results land; consumers like joblib wait
            # on the callback, never calling get() — so resolve eagerly.
            self._bg = threading.Thread(target=self._resolve,
                                        args=(None,), daemon=True)
            self._bg.start()

    def _resolve(self, timeout: Optional[float]):
        with self._lock:
            if self._done.is_set():
                return
            import ray_tpu
            try:
                chunks = ray_tpu.get(self._refs, timeout=timeout)
                out = list(itertools.chain.from_iterable(chunks))
                self._result = out[0] if self._single else out
            except ray_tpu.GetTimeoutError:
                raise TimeoutError("result not ready within timeout")
            except Exception as e:  # noqa: BLE001 - surfaced via get()
                self._error = e
                if self._error_callback is not None:
                    try:
                        self._error_callback(e)
                    except Exception:  # noqa: BLE001 - must reach done.set
                        import logging
                        logging.getLogger(__name__).exception(
                            "AsyncResult error_callback raised")
            else:
                # stdlib mp.Pool never converts a user-callback failure
                # into a job failure — run it outside the job try/except
                if self._callback is not None:
                    try:
                        self._callback(self._result)
                    except Exception:  # noqa: BLE001 - log, don't fail job
                        import logging
                        logging.getLogger(__name__).exception(
                            "AsyncResult callback raised")
            self._done.set()

    def get(self, timeout: Optional[float] = None):
        if self._bg is not None:
            # a background resolver owns the lock for the whole job —
            # wait on the completion event so `timeout` is honored
            if not self._done.wait(timeout):
                raise TimeoutError("result not ready within timeout")
        else:
            self._resolve(timeout)
        if self._error is not None:
            raise self._error
        return self._result

    def wait(self, timeout: Optional[float] = None):
        if self._bg is not None:
            self._done.wait(timeout)
            return
        try:
            self._resolve(timeout)
        except TimeoutError:
            pass

    def ready(self) -> bool:
        if self._done.is_set():
            return True
        import ray_tpu
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs),
                                timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result is not ready")
        return self._error is None


class IMapIterator:
    """Iterator over chunk results; ordered or completion-ordered."""

    def __init__(self, refs: list, ordered: bool):
        self._refs = list(refs)
        self._ordered = ordered
        self._buffer: list = []

    def __iter__(self):
        return self

    def __next__(self):
        import ray_tpu
        if self._buffer:
            return self._buffer.pop(0)
        if not self._refs:
            raise StopIteration
        if self._ordered:
            ref = self._refs.pop(0)
        else:
            ready, rest = ray_tpu.wait(self._refs, num_returns=1)
            ref = ready[0]
            self._refs = rest
        self._buffer.extend(ray_tpu.get(ref))
        return self.__next__()

    next = __next__


class Pool:
    """Process-pool-compatible API backed by ray_tpu actors.

    Reference: python/ray/util/multiprocessing/pool.py (Pool), which
    replaces fork workers with `PoolActor`s. Initializer runs once per
    worker actor; tasks are submitted as chunks to bound queue growth.
    """

    def __init__(self, processes: Optional[int] = None,
                 initializer: Optional[Callable] = None,
                 initargs: Sequence = (), maxtasksperchild=None,
                 ray_address: Optional[str] = None):
        import ray_tpu
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=ray_address)
        self._rt = ray_tpu
        if processes is None:
            res = ray_tpu.cluster_resources()
            processes = max(1, int(res.get("CPU", 2)))
        self._processes = processes
        self._closed = False

        @ray_tpu.remote
        class PoolActor:
            def __init__(self, initializer=None, initargs=()):
                if initializer is not None:
                    initializer(*initargs)

            def run(self, fn, chunk, star):
                if star:
                    return [fn(*item) for item in chunk]
                return [fn(item) for item in chunk]

            def ping(self):
                return True

        self._actors = [PoolActor.remote(initializer, tuple(initargs))
                        for _ in range(processes)]
        self._rr = 0  # round-robin cursor over pool actors

    # -- submission helpers ------------------------------------------------
    def _submit_chunks(self, fn, iterable, chunksize, star=False) -> list:
        if self._closed:
            raise ValueError("Pool not running")
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        refs = []
        for i in range(0, len(items), chunksize):
            actor = self._actors[self._rr % len(self._actors)]
            self._rr += 1
            refs.append(actor.run.remote(fn, items[i:i + chunksize], star))
        return refs

    # -- mp.Pool API -------------------------------------------------------
    def apply(self, fn: Callable, args: Sequence = (), kwds: dict = None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn: Callable, args: Sequence = (),
                    kwds: dict = None, callback=None, error_callback=None):
        kwds = kwds or {}
        call = _KwCall(fn, kwds) if kwds else fn
        refs = self._submit_chunks(call, [tuple(args)], 1, star=True)
        return AsyncResult(refs, single=True, pool=self,
                           callback=callback, error_callback=error_callback)

    def map(self, fn: Callable, iterable: Iterable, chunksize=None) -> List:
        return AsyncResult(self._submit_chunks(fn, iterable, chunksize),
                           single=False, pool=self).get()

    def map_async(self, fn, iterable, chunksize=None, callback=None,
                  error_callback=None) -> AsyncResult:
        return AsyncResult(self._submit_chunks(fn, iterable, chunksize),
                           single=False, pool=self, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn, iterable: Iterable[Sequence], chunksize=None):
        return AsyncResult(
            self._submit_chunks(fn, iterable, chunksize, star=True),
            single=False, pool=self).get()

    def starmap_async(self, fn, iterable, chunksize=None) -> AsyncResult:
        return AsyncResult(
            self._submit_chunks(fn, iterable, chunksize, star=True),
            single=False, pool=self)

    def imap(self, fn, iterable, chunksize=1) -> IMapIterator:
        return IMapIterator(self._submit_chunks(fn, iterable, chunksize),
                            ordered=True)

    def imap_unordered(self, fn, iterable, chunksize=1) -> IMapIterator:
        return IMapIterator(self._submit_chunks(fn, iterable, chunksize),
                            ordered=False)

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True
        for a in self._actors:
            try:
                self._rt.kill(a)
            except Exception:  # noqa: BLE001 - best-effort teardown
                pass
        self._actors = []

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")
        # close() drains nothing in this model: all submitted work holds
        # its own refs; nothing to wait on here beyond actor liveness.
        for a in self._actors:
            try:
                self._rt.get(a.ping.remote(), timeout=30)
            except Exception:  # noqa: BLE001
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _KwCall:
    """Picklable functools.partial-alike carrying kwargs for apply()."""

    def __init__(self, fn, kwds):
        self.fn = fn
        self.kwds = kwds

    def __call__(self, *args):
        return self.fn(*args, **self.kwds)
