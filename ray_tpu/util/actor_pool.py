"""ActorPool (reference capability: python/ray/util/actor_pool.py —
map/map_unordered/submit/get_next over a fixed set of actors)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional


class ActorPool:
    def __init__(self, actors: list):
        import ray_tpu
        self._rt = ray_tpu
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn: Callable, value) -> None:
        """fn(actor, value) -> ObjectRef."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = (self._next_task_index, actor, fn)
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def get_next(self, timeout: Optional[float] = None):
        """Next result in submission order."""
        if self._next_return_index not in self._index_to_future:
            raise StopIteration("no more results")
        future = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        idx, actor, fn = self._future_to_actor.pop(future)
        try:
            return self._rt.get(future, timeout=(300 if timeout is None else timeout))
        finally:
            self._return_actor(actor)

    def get_next_unordered(self, timeout: Optional[float] = None):
        if not self._future_to_actor:
            raise StopIteration("no more results")
        ready, _ = self._rt.wait(list(self._future_to_actor),
                                 num_returns=1, timeout=(300 if timeout is None else timeout))
        if not ready:
            raise TimeoutError(
                f"no result became ready within {(300 if timeout is None else timeout)}s")
        future = ready[0]
        idx, actor, fn = self._future_to_actor.pop(future)
        self._index_to_future.pop(idx, None)
        try:
            return self._rt.get(future, timeout=(300 if timeout is None else timeout))
        finally:
            self._return_actor(actor)

    def map(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable):
        for v in values:
            self.submit(fn, v)
        while self._future_to_actor or self._pending_submits:
            yield self.get_next_unordered()
