"""joblib backend over ray_tpu (reference capability:
python/ray/util/joblib/ — `register_ray()` + `parallel_backend("ray")`
so sklearn grid-search etc. fan out over the cluster).

Implemented as a joblib ParallelBackendBase subclass when joblib is
importable; `register_ray()` is a no-op with a warning otherwise (no new
dependencies may be installed in this environment).
"""

from __future__ import annotations

import warnings

_registered = False


def register_ray() -> None:
    """Register the 'ray' joblib backend (idempotent)."""
    global _registered
    if _registered:
        return
    try:
        from joblib.parallel import register_parallel_backend
    except ImportError:
        warnings.warn("joblib is not installed; register_ray() is a no-op")
        return
    register_parallel_backend("ray", _make_backend_class())
    _registered = True


def _make_backend_class():
    from joblib._parallel_backends import MultiprocessingBackend

    class RayBackend(MultiprocessingBackend):
        """joblib backend whose pool is ray_tpu.util.multiprocessing.Pool.

        joblib's MultiprocessingBackend drives an mp.Pool via apply_async;
        our Pool implements that surface, so the integration point is just
        configure() swapping the pool.
        """

        supports_timeout = True

        def effective_n_jobs(self, n_jobs):
            import ray_tpu
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            if n_jobs == 1:
                return 1
            cpus = int(ray_tpu.cluster_resources().get("CPU", 2))
            return cpus if n_jobs in (None, -1) else n_jobs

        def configure(self, n_jobs=1, parallel=None, prefer=None,
                      require=None, **memmappingpool_args):
            from ray_tpu.util.multiprocessing import Pool
            n_jobs = self.effective_n_jobs(n_jobs)
            self._pool = Pool(processes=n_jobs)
            self.parallel = parallel
            return n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()
                self._pool = None

    return RayBackend
