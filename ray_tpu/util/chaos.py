"""Chaos-testing utilities: kill random nodes while a workload runs.

Reference capability: the reusable NodeKiller resource actor
(python/ray/_private/test_utils.py:1337) and the release-test pattern
of killing nodes on an interval to prove recovery paths; surfaced on
the CLI as ``ray_tpu kill-random-node`` (the reference exposes the
same through chaos release tests).

TPU redesign delta: nodes here are event-loop services, so the killer
is a plain thread that either stops in-process ``NodeService`` objects
(virtual clusters) or sends the ``stop_node`` control message to a
remote node's listener.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Callable, Optional

from ray_tpu.core import protocol


def list_cluster_nodes(address: str) -> list[dict]:
    """[{node_id, address, alive}] from any node's state endpoint."""
    from ray_tpu.core.observer import observer_query
    return observer_query(address,
                          [{"t": "state", "what": "nodes"}])[0]["data"]


def kill_node_at(address: str) -> bool:
    """Send the stop_node kill switch to one node's listener."""
    from ray_tpu.core.observer import observer_connect
    try:
        conn, request = observer_connect(address, timeout=5.0)
    except (OSError, RuntimeError):
        return False
    try:
        request({"t": "stop_node"})
        return True
    except (protocol.ConnectionClosed, RuntimeError, TimeoutError):
        return True   # the node may die before flushing the ack
    finally:
        try:
            conn.close()
        except Exception:
            pass


def kill_random_node(address: str,
                     exclude_addresses: tuple = ()) -> Optional[str]:
    """Pick a random alive node (optionally sparing some, e.g. the
    driver's) and kill it.  Returns the victim's address or None."""
    nodes = [n for n in list_cluster_nodes(address)
             if n.get("alive") and n.get("address")
             and n["address"] not in exclude_addresses]
    if not nodes:
        return None
    victim = random.choice(nodes)
    return victim["address"] if kill_node_at(victim["address"]) else None


class NodeKiller:
    """Background chaos loop for virtual clusters (cluster_utils.Cluster):
    every `interval` seconds stop a random live node, optionally asking
    `replace` to add a fresh one so the cluster churns instead of
    draining to nothing."""

    def __init__(self, cluster, interval: float = 2.0,
                 max_kills: int = 1, exclude: tuple = (),
                 replace: Optional[Callable[[], None]] = None,
                 seed: Optional[int] = None):
        self.cluster = cluster
        self.interval = interval
        self.max_kills = max_kills
        self.exclude = set(id(n) for n in exclude)
        self.replace = replace
        self.rng = random.Random(seed)
        self.killed: list[str] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _candidates(self):
        return [n for n in self.cluster.nodes
                if id(n) not in self.exclude and not n._stop.is_set()]

    def _run(self):
        while not self._stop.is_set() and len(self.killed) < self.max_kills:
            if self._stop.wait(self.interval):
                break
            cands = self._candidates()
            if not cands:
                continue
            victim = self.rng.choice(cands)
            self.killed.append(victim.node_id.hex())
            self.cluster.kill_node(victim)
            if self.replace is not None:
                try:
                    self.replace()
                except Exception:
                    pass

    def start(self) -> "NodeKiller":
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="raytpu-node-killer")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
