"""ray_tpu.util: user utilities over the core API (reference capability:
python/ray/util — ActorPool, Queue, multiprocessing.Pool shim, joblib
backend, ParallelIterator, ray client, tracing; the collective API
lives in ray_tpu.parallel.collectives)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Queue", "Empty", "Full"]

# heavier util surfaces are import-on-demand submodules, mirroring the
# reference's layout: ray_tpu.util.multiprocessing.Pool,
# ray_tpu.util.joblib.register_ray, ray_tpu.util.iter.from_items,
# ray_tpu.util.client.connect, ray_tpu.util.tracing, ray_tpu.util.state
