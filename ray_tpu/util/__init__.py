"""ray_tpu.util: user utilities over the core API (reference capability:
python/ray/util — ActorPool, Queue; the collective API lives in
ray_tpu.parallel.collectives)."""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Queue", "Empty", "Full"]
