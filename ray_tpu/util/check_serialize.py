"""inspect_serializability: explain WHY an object fails to pickle.

Reference capability: python/ray/util/check_serialize.py —
``inspect_serializability(obj)`` walks closures/attributes of an
unpicklable object and prints a tree of the offending members, so
users can fix `@remote` capture errors without bisecting by hand.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional, Set, Tuple

import cloudpickle


class FailureTuple:
    """One identified unserializable member."""

    def __init__(self, obj: Any, name: str, parent: Any):
        self.obj = obj
        self.name = name
        self.parent = parent

    def __repr__(self):
        return f"FailureTuple({self.name}, parent={self.parent!r})"

    def __eq__(self, other):
        return (isinstance(other, FailureTuple)
                and (self.name, self.parent) == (other.name, other.parent))

    def __hash__(self):
        return hash((self.name, self.parent))


def _try_pickle(obj) -> Tuple[bool, Optional[Exception]]:
    try:
        cloudpickle.dumps(obj)
        return True, None
    except Exception as e:  # noqa: BLE001 - the point is diagnosing these
        return False, e


def inspect_serializability(obj: Any, name: Optional[str] = None,
                            depth: int = 3, _failures=None,
                            _seen: Optional[Set[int]] = None,
                            _print=print
                            ) -> Tuple[bool, Set[FailureTuple]]:
    """Returns (serializable, failures). Walks closure cells, attributes,
    and function globals of unpicklable objects up to `depth`."""
    failures = set() if _failures is None else _failures
    seen = set() if _seen is None else _seen
    name = name or getattr(obj, "__name__", repr(obj)[:60])

    ok, err = _try_pickle(obj)
    if ok:
        return True, failures
    if id(obj) in seen or depth < 0:
        return False, failures
    seen.add(id(obj))
    _print(f"  serialization FAILED for {name!r}: "
           f"{type(err).__name__}: {err}")

    children = []
    if inspect.isfunction(obj) or inspect.ismethod(obj):
        fn = obj.__func__ if inspect.ismethod(obj) else obj
        if fn.__closure__:
            for var, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    children.append((var, cell.cell_contents))
                except ValueError:
                    pass
        for g in fn.__code__.co_names:
            if g in (fn.__globals__ or {}):
                children.append((f"global:{g}", fn.__globals__[g]))
    else:
        for attr, val in sorted(vars(obj).items()) \
                if hasattr(obj, "__dict__") else []:
            children.append((attr, val))

    found_child = False
    for child_name, child in children:
        c_ok, _ = _try_pickle(child)
        if not c_ok:
            found_child = True
            failures.add(FailureTuple(child, child_name, name))
            inspect_serializability(child, name=child_name,
                                    depth=depth - 1, _failures=failures,
                                    _seen=seen, _print=_print)
    if not found_child:
        failures.add(FailureTuple(obj, name, None))
    return False, failures
