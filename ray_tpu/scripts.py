"""ray_tpu command line: start/stop/status/list/summary/timeline/memory.

The analogue of the reference CLI (reference: python/ray/scripts/
scripts.py:529 `ray start`, :1809 `ray status`, plus `ray list/summary/
timeline/memory` from python/ray/experimental/state/state_cli.py).
No pip entry point in this environment, so it runs as
``python -m ray_tpu <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import uuid


def _observer(address: str):
    from ray_tpu.core.observer import observer_connect
    return observer_connect(address)


def cmd_start(args) -> int:
    from ray_tpu._config import RayTpuConfig
    from ray_tpu.core.node import NodeService

    overrides = {}
    if args.metrics_port:
        overrides["metrics_export_port"] = args.metrics_port
    config = RayTpuConfig(overrides)
    session = uuid.uuid4().hex
    session_dir = os.path.join("/tmp/ray_tpu", f"session_{session[:8]}")

    head = None
    head_address = args.address
    if args.head:
        from ray_tpu.core.head import HeadService
        head = HeadService(config, session, port=args.port or 0)
        head.start_thread()
        head_address = head.address
        print(f"head service listening on {head.address}")
    elif not head_address:
        print("either --head or --address=<head> is required",
              file=sys.stderr)
        return 2

    node = NodeService(config, session, session_dir,
                       num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                       head_address=head_address,
                       stop_on_driver_exit=False)
    print(f"node service listening on {node.address} "
          f"(session {session[:8]})")
    if node.metrics_exporter is not None:
        print(f"metrics at http://127.0.0.1:"
              f"{node.metrics_exporter.port}/metrics")
    print("connect with: ray_tpu.init(address="
          f"{node.address!r})", flush=True)
    try:
        node.run()
    except KeyboardInterrupt:
        pass
    finally:
        # every exit path must reap workers/shm/metrics threads
        node.stop()
        if head is not None:
            head.stop()
    return 0


def cmd_stop(args) -> int:
    import signal
    import subprocess

    # match the module paths exactly (a looser pattern would match the
    # invoking shell; see repo verify notes)
    n = 0
    for pat in ("ray_tpu.core.worker", "ray_tpu.core.node",
                "ray_tpu.core.head", "ray_tpu start"):
        r = subprocess.run(["pkill", "-f", pat],
                           capture_output=True)
        n += 1 if r.returncode == 0 else 0
    print(f"stopped ({n} process groups signalled)")
    del signal
    return 0


def cmd_status(args) -> int:
    conn, request = _observer(args.address)
    try:
        nodes = request({"t": "state", "what": "nodes"})["data"]
        res = request({"t": "state", "what": "resources"})["data"]
        stats = request({"t": "object_stats"})["stats"]
    finally:
        conn.close()
    print("======== cluster status ========")
    print(f"nodes: {len(nodes)} "
          f"({sum(1 for n in nodes if n.get('alive'))} alive)")
    for n in nodes:
        mark = "+" if n.get("alive") else "-"
        print(f"  {mark} {n['node_id'][:12]} {n['address']} "
              f"avail={n['available']} total={n['resources']}")
    print(f"resources: available={res['available']} total={res['total']}")
    print(f"object store: {stats['num_objects']} objects, "
          f"{stats['used_bytes'] / 1e6:.1f}/"
          f"{stats['capacity_bytes'] / 1e6:.1f} MB used"
          + (", spilled=%d" % stats["num_spilled"]
             if stats.get("num_spilled") else ""))
    return 0


def cmd_list(args) -> int:
    conn, request = _observer(args.address)
    try:
        what = {"nodes": "nodes", "tasks": "tasks", "actors": "actors",
                "objects": "objects", "workers": "workers"}[args.what]
        data = request({"t": "state", "what": what})["data"]
    finally:
        conn.close()
    print(json.dumps(data, indent=2, default=str))
    return 0


def cmd_summary(args) -> int:
    conn, request = _observer(args.address)
    try:
        data = request({"t": "state", "what": args.what})["data"]
    finally:
        conn.close()
    from ray_tpu.util.state import group_counts
    key = {"tasks": "name", "actors": "class_name",
           "objects": "loc"}[args.what]
    summ = group_counts(data, key)
    for name, states in summ["cluster"].items():
        print(f"{name}: {states}")
    print(f"total: {summ['total']}")
    return 0


def cmd_timeline(args) -> int:
    """Merged Perfetto export: task events + flight-recorder lifecycle
    stages + tracing spans + chaos events in one trace (reference:
    ray.timeline Chrome-trace export)."""
    conn, request = _observer(args.address)
    try:
        events = request({"t": "state", "what": "task_events"})["data"]
        # export the recorder's WHOLE ring, not the server default
        fr = request({"t": "flight_recorder", "limit": 1_000_000})
    finally:
        conn.close()
    spans = []
    trace_dir = getattr(args, "trace_dir", None) \
        or os.environ.get("RAY_TPU_TRACE_DIR")
    if trace_dir:
        from ray_tpu.util.tracing import collect_spans
        spans = collect_spans(trace_dir)
    # serve-fleet ingress events: from the armed flight recorder, plus
    # any Fleet.dump_events file (ingress processes that ran without a
    # recorder — e.g. the trace-replay harness)
    ingress = list(fr.get("ingress", []))
    serve_events = getattr(args, "serve_events", None)
    if serve_events:
        with open(serve_events) as f:
            ingress += json.load(f)
    from ray_tpu.util.timeline import build_trace
    trace = build_trace(task_events=events,
                        records=fr.get("records", []),
                        spans=spans,
                        faults=fr.get("faults", []),
                        ingress=ingress)
    out = args.output or f"timeline-{int(time.time())}.json"
    with open(out, "w") as f:
        json.dump(trace, f)
    n = len(trace["traceEvents"])
    lifecycle = sum(1 for e in trace["traceEvents"]
                    if e.get("cat") == "lifecycle")
    n_ingress = sum(1 for e in trace["traceEvents"]
                    if e.get("cat") == "ingress")
    print(f"wrote {n} events ({lifecycle} lifecycle stage slices, "
          f"{n_ingress} ingress events) to "
          f"{out} (open in chrome://tracing or ui.perfetto.dev)"
          + ("" if fr.get("enabled") else
             "; flight recorder disabled — set "
             "RAY_TPU_FLIGHT_RECORDER=1 for per-stage slices"))
    return 0


def cmd_memory(args) -> int:
    conn, request = _observer(args.address)
    try:
        stats = request({"t": "object_stats"})
        objects = request({"t": "state", "what": "objects"})["data"]
    finally:
        conn.close()
    print(json.dumps(stats["stats"], indent=2))
    biggest = sorted(objects, key=lambda o: -(o.get("size") or 0))[:20]
    for o in biggest:
        print(f"  {o['object_id'][:16]} {o['state']:8} "
              f"{o.get('loc') or '-':7} {(o.get('size') or 0) / 1e6:.2f} MB")
    return 0


def cmd_stack(args) -> int:
    """Dump the thread stacks of every live worker on a node
    (reference: `ray stack`, scripts.py:1767)."""
    conn, request = _observer(args.address)
    try:
        workers = request({"t": "state", "what": "workers"})["data"]
        workers = [w for w in workers if w["kind"] == "worker"]
        if not workers:
            print("no live workers on this node")
            return 0
        for w in workers:
            print(f"===== worker pid={w['pid']} state={w['state']} =====")
            try:
                r = request({"t": "stack_dump", "pid": w["pid"]})
                print(r.get("data", ""))
            except RuntimeError as e:
                print(f"  <{e}>")
        return 0
    finally:
        conn.close()


def cmd_flame(args) -> int:
    """Flamegraph a live worker by pid (folded stacks sampled in the
    worker; rendered here)."""
    from ray_tpu.core.observer import observer_query
    from ray_tpu.util.profiling import flamegraph_svg
    (reply,) = observer_query(
        args.address,
        [{"t": "profile_worker", "pid": args.pid,
          "duration": args.duration}],
        request_timeout=args.duration + 40)
    folded = reply.get("folded", "")
    with open(args.output, "w") as f:
        f.write(flamegraph_svg(folded))
    n = len([ln for ln in folded.splitlines() if ln.strip()])
    print(f"wrote {args.output} ({n} distinct stacks)")
    return 0


def cmd_kill_random_node(args) -> int:
    from ray_tpu.util.chaos import kill_random_node
    victim = kill_random_node(args.address,
                              exclude_addresses=tuple(args.spare))
    if victim is None:
        print("no killable node found")
        return 1
    print(f"killed node at {victim}")
    return 0


def cmd_dashboard(args) -> int:
    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(args.address, port=args.port)
    dash.start()
    print(f"dashboard at http://{dash.host}:{dash.port}/", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        dash.stop()
    return 0


def cmd_job(args) -> int:
    from ray_tpu.job import JobStatus, JobSubmissionClient

    client = JobSubmissionClient(address=args.address)
    if args.job_cmd == "submit":
        runtime_env = {}
        if args.working_dir:
            runtime_env["working_dir"] = args.working_dir
        import shlex
        job_id = client.submit_job(
            entrypoint=shlex.join(args.entrypoint),
            runtime_env=runtime_env or None)
        print(f"submitted {job_id}")
        if args.wait:
            status = client.wait_until_finished(job_id,
                                                timeout=args.timeout)
            print(client.get_job_logs(job_id), end="")
            print(f"status: {status}")
            return 0 if status == JobStatus.SUCCEEDED else 1
        return 0
    if args.job_cmd == "status":
        print(client.get_job_status(args.job_id))
        return 0
    if args.job_cmd == "logs":
        print(client.get_job_logs(args.job_id), end="")
        return 0
    if args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.job_id)
              else "not running")
        return 0
    if args.job_cmd == "list":
        for info in client.list_jobs():
            print(f"{info.job_id}  {info.status:10}  {info.entrypoint}")
        return 0
    return 2


def cmd_serve(args) -> int:
    """`serve run/deploy/status/shutdown` (reference:
    python/ray/serve/scripts.py serve CLI)."""
    import json as _json

    if args.serve_cmd == "run":
        import ray_tpu
        from ray_tpu.serve.rest import ServeRestServer, apply_config
        ray_tpu.init(address=args.address)
        apply_config({"applications": [
            {"name": args.name or args.import_path,
             "import_path": args.import_path}]},
            http=True, port=args.port)
        from ray_tpu import serve as _serve
        rest = ServeRestServer(port=args.rest_port)
        print(f"serving {args.import_path}  "
              f"ingress={_serve.proxy_address()}  rest={rest.address}")
        # always block: the proxy/REST servers are daemon threads of
        # THIS process — returning would tear the service down
        import time as _time
        try:
            while True:
                _time.sleep(1)
        except KeyboardInterrupt:
            pass
        return 0

    if args.serve_cmd == "deploy":
        import urllib.request
        with open(args.config_file) as f:
            cfg = (_json.load(f) if args.config_file.endswith(".json")
                   else _load_yaml_or_json(f.read()))
        req = urllib.request.Request(
            args.address.rstrip("/") + "/api/serve/applications/",
            data=_json.dumps(cfg).encode(), method="PUT",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            print(resp.read().decode())
        return 0

    if args.serve_cmd == "status":
        import urllib.request
        with urllib.request.urlopen(
                args.address.rstrip("/") + "/api/serve/applications/",
                timeout=30) as resp:
            print(_json.dumps(_json.loads(resp.read()), indent=2))
        return 0

    if args.serve_cmd == "shutdown":
        import urllib.request
        req = urllib.request.Request(
            args.address.rstrip("/") + "/api/serve/applications/",
            method="DELETE")
        with urllib.request.urlopen(req, timeout=60):
            print("shut down")
        return 0
    return 2


def cmd_up(args) -> int:
    """`ray_tpu up cluster.yaml` (reference: scripts.py up:1216)."""
    from ray_tpu.autoscaler.commands import load_cluster_config, up
    up(load_cluster_config(args.cluster_config))
    return 0


def cmd_down(args) -> int:
    from ray_tpu.autoscaler.commands import down, load_cluster_config
    down(load_cluster_config(args.cluster_config),
         keep_head=args.keep_head)
    return 0


def cmd_attach(args) -> int:
    from ray_tpu.autoscaler.commands import attach, load_cluster_config
    return attach(load_cluster_config(args.cluster_config))


def cmd_exec(args) -> int:
    from ray_tpu.autoscaler.commands import exec_cmd, load_cluster_config
    out = exec_cmd(load_cluster_config(args.cluster_config),
                   " ".join(args.command),
                   on_head=not args.workers,
                   all_workers=args.all_hosts)
    print(out, end="" if out.endswith("\n") else "\n")
    return 0


def cmd_submit(args) -> int:
    from ray_tpu.autoscaler.commands import load_cluster_config, submit
    out = submit(load_cluster_config(args.cluster_config), args.script)
    print(out, end="" if out.endswith("\n") else "\n")
    return 0


def _load_yaml_or_json(text: str) -> dict:
    import json as _json
    try:
        return _json.loads(text)
    except ValueError:
        try:
            import yaml
            return yaml.safe_load(text)
        except ImportError as e:
            raise SystemExit(
                "config is not JSON and pyyaml is unavailable") from e


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ray_tpu",
        description="ray_tpu cluster CLI (reference: `ray` CLI surface)")
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("start", help="start a head and/or node service")
    p.add_argument("--head", action="store_true",
                   help="start a head service (plus a node joined to it)")
    p.add_argument("--address", default=None,
                   help="existing head address to join")
    p.add_argument("--port", type=int, default=0, help="head listen port")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--num-tpus", type=float, default=None)
    p.add_argument("--metrics-port", type=int, default=0)
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("stop", help="kill local ray_tpu processes")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("up", help="launch a cluster from a YAML config "
                                  "(reference: `ray up`)")
    p.add_argument("cluster_config")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down", help="tear a launched cluster down")
    p.add_argument("cluster_config")
    p.add_argument("--keep-head", action="store_true")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("attach", help="interactive shell on the head")
    p.add_argument("cluster_config")
    p.set_defaults(fn=cmd_attach)

    p = sub.add_parser("exec", help="run a shell command on the cluster")
    p.add_argument("cluster_config")
    p.add_argument("--workers", action="store_true",
                   help="run on worker nodes instead of the head")
    p.add_argument("--all-hosts", action="store_true",
                   help="every host of a multi-host slice")
    p.add_argument("command", nargs="+")
    p.set_defaults(fn=cmd_exec)

    p = sub.add_parser("submit", help="copy a script to the head and "
                                      "run it (reference: `ray submit`)")
    p.add_argument("cluster_config")
    p.add_argument("script")
    p.set_defaults(fn=cmd_submit)

    for name, fn in (("status", cmd_status), ("memory", cmd_memory)):
        p = sub.add_parser(name)
        p.add_argument("--address", required=True)
        p.set_defaults(fn=fn)

    p = sub.add_parser("list", help="list tasks/actors/objects/workers/nodes")
    p.add_argument("what", choices=["tasks", "actors", "objects",
                                    "workers", "nodes"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("summary")
    p.add_argument("what", choices=["tasks", "actors", "objects"])
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_summary)

    p = sub.add_parser("timeline",
                       help="merged Perfetto trace: task events + "
                            "flight-recorder stages + spans + chaos + "
                            "serve-fleet ingress events")
    p.add_argument("--address", required=True)
    p.add_argument("-o", "--output", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="RAY_TPU_TRACE_DIR to merge span files from")
    p.add_argument("--serve-events", default=None,
                   help="Fleet.dump_events JSON to merge ingress "
                        "admission/shed/route events from")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("stack", help="dump live worker thread stacks "
                                     "(reference: `ray stack`)")
    p.add_argument("--address", required=True)
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("flame", help="sampling-profile a worker into a "
                                     "flamegraph SVG (reference: the "
                                     "dashboard's py-spy profiling)")
    p.add_argument("--address", required=True)
    p.add_argument("--pid", type=int, required=True)
    p.add_argument("--duration", type=float, default=2.0)
    p.add_argument("-o", "--output", default="flame.svg")
    p.set_defaults(fn=cmd_flame)

    p = sub.add_parser("kill-random-node",
                       help="chaos: hard-stop a random alive node "
                            "(reference: chaos release tests / "
                            "test_utils NodeKiller)")
    p.add_argument("--address", required=True,
                   help="any cluster node's address")
    p.add_argument("--spare", action="append", default=[],
                   help="node address to never kill (repeatable)")
    p.set_defaults(fn=cmd_kill_random_node)

    from ray_tpu.analysis.cli import add_parser as _add_lint
    _add_lint(sub)

    p = sub.add_parser("dashboard", help="serve the web dashboard")
    p.add_argument("--address", required=True)
    p.add_argument("--port", type=int, default=8265)
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("job", help="submit/inspect cluster jobs")
    jsub = p.add_subparsers(dest="job_cmd", required=True)
    ps = jsub.add_parser("submit")
    ps.add_argument("--address", required=True)
    ps.add_argument("--working-dir", default=None)
    ps.add_argument("--wait", action="store_true")
    ps.add_argument("--timeout", type=float, default=600.0)
    ps.add_argument("entrypoint", nargs="+",
                    help="command to run on the cluster (after --)")
    ps.set_defaults(fn=cmd_job)
    for name in ("status", "logs", "stop"):
        pj = jsub.add_parser(name)
        pj.add_argument("--address", required=True)
        pj.add_argument("job_id")
        pj.set_defaults(fn=cmd_job)
    pl = jsub.add_parser("list")
    pl.add_argument("--address", required=True)
    pl.set_defaults(fn=cmd_job)

    p = sub.add_parser("serve", help="model-serving CLI")
    ssub = p.add_subparsers(dest="serve_cmd", required=True)
    pr = ssub.add_parser("run", help="deploy module:app and serve HTTP")
    pr.add_argument("import_path")
    pr.add_argument("--name", default=None)
    pr.add_argument("--address", default=None,
                    help="cluster address (default: local node)")
    pr.add_argument("--port", type=int, default=8000)
    pr.add_argument("--rest-port", type=int, default=8001)
    pr.set_defaults(fn=cmd_serve)
    pd = ssub.add_parser("deploy", help="PUT a config to a serve REST API")
    pd.add_argument("config_file")
    pd.add_argument("--address", required=True,
                    help="serve REST address, e.g. http://host:8001")
    pd.set_defaults(fn=cmd_serve)
    for name in ("status", "shutdown"):
        psx = ssub.add_parser(name)
        psx.add_argument("--address", required=True)
        psx.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
