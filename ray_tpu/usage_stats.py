"""Usage stats: opt-out local usage records (no network egress).

Reference capability: python/ray/_private/usage/usage_lib.py — an
opt-out telemetry ping summarizing cluster/library usage. Re-derived
WITHOUT phoning home: records are written to a local JSONL file under
the session dir so operators can aggregate them themselves; nothing
leaves the machine. Disable with RAY_TPU_USAGE_STATS_ENABLED=0
(mirrors RAY_USAGE_STATS_ENABLED).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, Optional

_lock = threading.Lock()
_tags: Dict[str, str] = {}
_library_usages: set = set()


def usage_stats_enabled() -> bool:
    return os.environ.get("RAY_TPU_USAGE_STATS_ENABLED", "1").lower() \
        not in ("0", "false", "no")


def record_library_usage(library: str) -> None:
    """Called by library entry points (train/tune/data/serve/rllib)
    (reference: usage_lib.record_library_usage)."""
    if not usage_stats_enabled():
        return
    with _lock:
        _library_usages.add(library)


def record_extra_usage_tag(key: str, value: str) -> None:
    if not usage_stats_enabled():
        return
    with _lock:
        _tags[str(key)] = str(value)


def _snapshot() -> dict:
    import ray_tpu
    with _lock:
        snap = {
            "ts": time.time(),
            "version": ray_tpu.__version__,
            "libraries": sorted(_library_usages),
            "tags": dict(_tags),
        }
    try:
        import jax
        snap["device_kind"] = jax.devices()[0].device_kind
        snap["n_devices"] = len(jax.devices())
    except Exception:  # noqa: BLE001 - no backend is fine
        pass
    return snap


def write_usage_record(session_dir: Optional[str] = None) -> Optional[str]:
    """Append one usage record locally (the analogue of the reference's
    report, minus the network)."""
    if not usage_stats_enabled():
        return None
    d = session_dir or os.path.join("/tmp/ray_tpu", "usage")
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "usage_stats.jsonl")
    with open(path, "a") as f:
        f.write(json.dumps(_snapshot()) + "\n")
    return path
