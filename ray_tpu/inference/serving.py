"""Serve deployment for the inference engine: POST /v1/generate.

Each replica owns one InferenceEngine (its own cache pool + decode
loop); Serve's router spreads requests over replicas and the
AutoscalingConfig grows/shrinks the replica set on per-replica in-flight
load — which for this deployment IS the engine queue depth, since every
in-flight request is either holding a decode slot or parked in the
engine's admission queue.  `max_concurrent_queries` is set well above
`max_slots` so the engine (not the router) does the queueing and the
continuous-batching loop sees the real backlog.

Request JSON (POST /v1/generate — any /v1/* path routes here, the
deployment is named "v1"):

    {"prompt": [1, 2, 3] | "text",     # token ids, or a string encoded
                                       #   bytewise modulo the vocab
     "max_tokens": 16,                 # default engine_cfg.default_max_new
     "temperature": 0.0,               # 0 = greedy
     "seed": 0,
     "stream": false,
     "priority": "interactive",        # or "batch" (default): engine
                                       #   admission + ingress queue class
     "model": "variant-id"}            # multiplexed deployments only

Non-streaming replies {"tokens": [...], "n": n, "ttft_s": ..., ...};
``stream: true`` returns a generator the asyncio proxy flushes as
chunked transfer-encoding — one JSON document per chunk, each carrying
one token, then a final ``{"done": true, ...}`` chunk.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence, Union

import jax

from ray_tpu.inference.engine import (EngineConfig, EngineDrainingError,
                                      EngineStoppedError, InferenceEngine,
                                      parse_priority)
from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.serve.deployment import (AutoscalingConfig, Deployment,
                                      DeploymentOptions)

DEFAULT_ROUTE = "v1"


def encode_prompt(prompt: Union[str, Sequence[int]],
                  vocab_size: int) -> list[int]:
    """Token ids pass through; strings encode bytewise modulo the vocab
    (the repo ships no tokenizer — this keeps the HTTP surface usable
    end-to-end and is trivially reversible for vocab >= 256)."""
    if isinstance(prompt, str):
        if not prompt:
            raise ValueError("empty prompt")
        return [b % vocab_size for b in prompt.encode("utf-8")]
    return [int(t) for t in prompt]


class GPTServer:
    """Replica body: one engine per replica — or, with ``variants``, an
    LRU of per-variant engines (model multiplexing behind one
    deployment).

    Params are derived from ``seed`` at replica init (deterministic
    across replicas, so any replica answers any request identically
    under greedy decoding — the property the fleet's
    resume-on-replica-death replay relies on), or passed in directly
    for in-process use.  When built under the serve controller the
    replica tag names the engine(s) and labels their /metrics series.
    """

    def __init__(self, cfg: Optional[GPTConfig] = None,
                 engine_cfg: Optional[EngineConfig] = None,
                 seed: int = 0, params=None,
                 engine_name: Optional[str] = None,
                 variants: Optional[dict] = None,
                 multiplex_capacity: int = 2,
                 warm_on_init: bool = False,
                 mesh=None, rules=None):
        self.cfg = cfg or GPTConfig.tiny()
        self.engine_cfg = engine_cfg or EngineConfig()
        # tensor-parallel serving: every engine this replica builds
        # (multiplexed variants included) shares the one mesh — pools
        # heads-sharded, tables/radix replicated (see inference.decode)
        self.mesh = mesh
        self.rules = rules
        self._warm = warm_on_init
        self._closed = False
        self._draining = False
        from ray_tpu.serve.controller import get_replica_context
        ctx = get_replica_context()
        self.replica_tag = (ctx.replica_tag if ctx is not None
                            else (engine_name or ""))
        self._labels = ({"deployment": ctx.deployment,
                         "replica": ctx.replica_tag}
                        if ctx is not None else {})
        self._mux = None
        self.engine = None
        if variants and params is not None:
            raise ValueError(
                "params and variants are mutually exclusive: each "
                "variant derives its own params from its catalog seed")
        if variants:
            # model multiplexing: model_id -> seed (each variant is an
            # independently seeded param set + engine/KV pool);
            # LRU-resident per replica, the fleet router prefers
            # replicas already holding the requested variant
            from ray_tpu.serve.fleet.multiplex import ModelMultiplexer
            self._mux = ModelMultiplexer(
                variants,
                lambda mid, spec: self._build_engine(mid, int(spec)),
                lambda eng: eng.shutdown(timeout=2.0),
                capacity=multiplex_capacity)
            # default variant resident from birth; a WARM replica
            # preloads a full working set so scale-up cost stays in the
            # controller, not head-of-line on the first requests
            preload = (list(variants)[:multiplex_capacity]
                       if warm_on_init else [None])
            for mid in preload:
                self._mux.get(mid)
        else:
            self.engine = self._build_engine(None, seed, params=params,
                                             name_override=engine_name)

    def _build_engine(self, model_id: Optional[str], seed: int,
                      params=None, name_override=None) -> InferenceEngine:
        if params is None:
            params = gpt.init_params(self.cfg, jax.random.PRNGKey(seed))
        name = name_override
        if name is None and self.replica_tag:
            name = self.replica_tag + (f":{model_id}" if model_id else "")
        labels = dict(self._labels)
        if model_id:
            labels["model"] = model_id
        kw = {}
        if self.mesh is not None:
            kw["mesh"] = self.mesh
            if self.rules is not None:
                kw["rules"] = self.rules
        eng = InferenceEngine(params, self.cfg, self.engine_cfg,
                              name=name, labels=labels, **kw)
        if self._warm:
            # compile prefill+decode off the request path, so a freshly
            # scaled-up replica doesn't serve its first requests cold
            eng.generate([1], max_new=2, timeout=300)
        return eng

    def _engine_for(self, req: dict) -> InferenceEngine:
        if self._closed:
            raise EngineStoppedError("replica closed")
        if self._draining:
            # the route/drain race window: the router picked this
            # replica just as the controller marked it DRAINING — the
            # typed error re-routes (never a 500, never a failure count)
            raise EngineDrainingError("replica is draining (scale-down)")
        if self._mux is None:
            return self.engine
        return self._mux.get(req.get("model"))

    def __call__(self, req):
        if not isinstance(req, dict):
            raise ValueError(
                "expected a JSON object body, e.g. "
                '{"prompt": [1, 2, 3], "max_tokens": 16}')
        if "prompt" not in req:
            raise ValueError('missing required field "prompt"')
        prompt = encode_prompt(req["prompt"], self.cfg.vocab_size)
        handle = self._engine_for(req).submit(
            prompt,
            max_new=req.get("max_tokens"),
            temperature=float(req.get("temperature", 0.0)),
            seed=int(req.get("seed", 0)),
            priority=parse_priority(req.get("priority")))
        if req.get("stream"):
            return self._stream(handle)
        try:
            toks = handle.result(timeout=float(req.get("timeout", 120.0)))
        except TimeoutError:
            # shed the abandoned generation: nobody will read it, so it
            # must not keep holding a decode slot against live requests
            handle.cancel()
            raise
        return {
            "tokens": toks,
            "n": len(toks),
            "ttft_s": (handle.first_token_s or 0) - handle.created_s,
            "latency_s": (handle.finished_s or 0) - handle.created_s,
        }

    @staticmethod
    def _stream(handle):
        def gen():
            i = 0
            try:
                for tok in handle.stream():
                    yield {"token": int(tok), "index": i}
                    i += 1
                yield {"done": True, "n": i,
                       "latency_s": (handle.finished_s or 0)
                       - handle.created_s}
            finally:
                # client disconnect mid-stream closes the generator
                # (GeneratorExit lands here): stop decoding for nobody
                if not handle.done:
                    handle.cancel()
        return gen()

    def _engines(self) -> list:
        if self._mux is not None:
            return self._mux.loaded_bodies()
        return [self.engine] if self.engine is not None else []

    # surfaced for tests / the metrics endpoint via the engine registry
    def engine_stats(self):
        if self._mux is not None:
            raise RuntimeError("multiplexed replica: use fleet_stats()")
        return self.engine.stats()

    def fleet_stats(self) -> dict:
        """The router's probe surface: engine load + loaded variants.
        Multiplexed replicas aggregate over resident engines (total
        slots grow with residency — the router sees real capacity)."""
        engines = self._engines()
        stats = [e.stats() for e in engines]
        blocks_total = sum(s.get("blocks_total", 0) for s in stats)
        blocks_free = sum(s.get("blocks_free", 0) for s in stats)
        hit = sum(s.get("prefix_hit_tokens", 0) for s in stats)
        lookup = sum(s.get("prefix_lookup_tokens", 0) for s in stats)
        drafted = sum(s.get("spec_drafted_tokens", 0) for s in stats)
        s_accept = sum(s.get("spec_accepted_tokens", 0) for s in stats)
        row_steps = sum(s.get("row_steps", 0) for s in stats)
        row_tokens = sum(s.get("row_tokens", 0) for s in stats)
        return {
            "max_slots": sum(s["max_slots"] for s in stats),
            "active_slots": sum(s["active_slots"] for s in stats),
            "waiting_requests": sum(s["waiting_requests"] for s in stats),
            "waiting_interactive": sum(s["waiting_interactive"]
                                       for s in stats),
            # paged-cache capacity signal: the occupancy router and the
            # autoscaler see BLOCK pressure, not just row counts — a
            # replica whose rows are free but whose pool is nearly full
            # is not actually spare capacity (0s when every engine runs
            # the legacy slot pool)
            # block counts are GLOBAL admission budgets (replicated in
            # count across tp shards — heads are what's split), so
            # summing across engines needs no per-shard correction
            "blocks_total": blocks_total,
            "blocks_free": blocks_free,
            "block_utilization": ((blocks_total - blocks_free)
                                  / blocks_total if blocks_total else 0.0),
            # serving geometry: devices under this replica's engines
            # (max, not sum — multiplexed engines share the one mesh)
            "mesh_devices": max((s.get("mesh_devices", 1)
                                 for s in stats), default=1),
            "tp_shards": max((s.get("tp_shards", 1)
                              for s in stats), default=1),
            "prefix_hit_tokens": hit,
            "prefix_lookup_tokens": lookup,
            "prefix_hit_rate": (hit / lookup) if lookup else 0.0,
            # speculative decoding: the router and autoscaler see the
            # replica's accept-rate and per-row decode throughput (1.0
            # without speculation — same-run baselines stay comparable)
            "spec_drafted_tokens": drafted,
            "spec_accepted_tokens": s_accept,
            "spec_accept_rate": (s_accept / drafted) if drafted else 0.0,
            "tokens_per_step": (row_tokens / row_steps) if row_steps
                               else 0.0,
            "models": (self._mux.loaded_models()
                       if self._mux is not None else []),
            "stopped": self._closed or not engines
            or all(s["stopped"] for s in stats),
            # replica-LEVEL drain flag: the router skips draining
            # replicas as candidates without dead-marking them (they are
            # alive — just not accepting new work).  Deliberately NOT
            # derived from the engines' own draining flags: an engine
            # drained out-of-band is the route/drain race, and the typed
            # EngineDrainingError out of submit() is what covers it.
            "draining": self._draining,
        }

    # --------------------------------------------- cluster prefix plane
    # Replica-body surface of serve/fleet/prefix_directory.py: the fleet
    # calls these through the same handle plumbing as __call__, so for
    # actor replicas the K/V payload rides the existing object/transfer
    # plane.  All failure modes are typed (PrefixTransferError /
    # ReplicaDeadError shapes) and the plane maps every one of them to
    # local-recompute fallback.

    def prefix_export(self) -> list:
        """Drain all resident engines' prefix publication outboxes
        (tagged with the request ``model`` for multiplexed replicas)."""
        if self._closed:
            return []
        out = []
        if self._mux is not None:
            for mid, eng in zip(self._mux.loaded_models(),
                                self._mux.loaded_bodies()):
                for ex in eng.prefix_export():
                    ex["model"] = mid
                    out.append(ex)
        elif self.engine is not None:
            out.extend(self.engine.prefix_export())
        return out

    def prefix_extract(self, model, tokens, generation: int) -> dict:
        """Holder side of replica→replica prefix adoption (see
        InferenceEngine.prefix_extract for the validation ladder)."""
        req = {"model": model} if model is not None else {}
        return self._engine_for(req).prefix_extract(tokens, generation)

    def prefix_install(self, model, tokens, payload: dict) -> dict:
        """Adopter side: install fetched K/V blocks into the local
        radix index (see InferenceEngine.prefix_install)."""
        req = {"model": model} if model is not None else {}
        return self._engine_for(req).prefix_install(tokens, payload)

    def loaded_variants(self) -> list:
        return self._mux.loaded_models() if self._mux is not None else []

    def multiplex_stats(self) -> Optional[dict]:
        return self._mux.stats() if self._mux is not None else None

    def drain(self) -> None:
        """Replica drain hook (DeploymentState.drain_replicas): stop
        admitting — queued engine waiters are handed back as
        EngineDrainingError for re-routing — while in-flight slots
        decode to completion.  The controller polls ``fleet_stats``
        until active_slots reaches 0 (or the drain deadline) before
        tearing the replica down."""
        self._draining = True
        for eng in self._engines():
            eng.drain()

    def health(self):
        st = self.fleet_stats()
        return not st["stopped"]

    def teardown(self):
        """Replica teardown hook (DeploymentState.scale_to): stop the
        engine loop(s) so a scaled-down replica releases its KV pool
        and thread instead of leaking them."""
        self._closed = True
        if self._mux is not None:
            self._mux.unload_all()
        elif self.engine is not None:
            self.engine.shutdown(timeout=2.0)

    def __del__(self):   # best-effort: teardown() is the real path
        try:
            for eng in self._engines():
                eng.shutdown(timeout=0.5)
        except Exception:
            pass


def build_gpt_deployment(*, name: str = DEFAULT_ROUTE,
                         cfg: Optional[GPTConfig] = None,
                         engine_cfg: Optional[EngineConfig] = None,
                         seed: int = 0,
                         num_replicas: int = 1,
                         max_concurrent_queries: int = 64,
                         autoscaling: Optional[AutoscalingConfig] = None,
                         params=None,
                         variants: Optional[dict] = None,
                         multiplex_capacity: int = 2,
                         warm_on_init: bool = False,
                         mesh=None, rules=None) -> Deployment:
    """A ready-to-``serve.run`` deployment wrapping GPTServer.  Route is
    /<name>/... — the default name "v1" makes POST /v1/generate work.

    Pass ``autoscaling`` (e.g. AutoscalingConfig(min_replicas=1,
    max_replicas=4, target_ongoing_requests=max_slots)) to scale the
    replica set on queue depth; each new replica brings its own engine
    and cache pool.  ``variants`` ({model_id: seed}) turns each replica
    into a model-multiplexed server: at most ``multiplex_capacity``
    variants resident per replica, LRU-evicted; requests pick one with
    the ``model`` field.  ``warm_on_init`` compiles prefill+decode at
    replica construction so scale-ups don't serve cold.  ``mesh`` (+
    optional ``rules``) serves every replica tensor-parallel: params
    and KV pools heads-sharded over the mesh's ``tp`` axis, one decode
    program shared across replicas of the same geometry.
    """
    return Deployment(
        GPTServer,
        DeploymentOptions(name=name, num_replicas=num_replicas,
                          max_concurrent_queries=max_concurrent_queries,
                          autoscaling=autoscaling),
        init_args=(),
        init_kwargs=dict(cfg=cfg, engine_cfg=engine_cfg, seed=seed,
                         params=params, variants=variants,
                         multiplex_capacity=multiplex_capacity,
                         warm_on_init=warm_on_init,
                         mesh=mesh, rules=rules))


def parse_stream_chunks(raw: bytes) -> list[dict]:
    """Decode the chunked-transfer JSON documents a streamed /v1/generate
    response carries (helper for clients and tests: one dict per chunk,
    in arrival order)."""
    out = []
    rest = raw
    while rest:
        head, _, rest = rest.partition(b"\r\n")
        if not head:
            continue
        n = int(head, 16)
        if n == 0:
            break
        out.append(json.loads(rest[:n]))
        rest = rest[n:]
        if rest.startswith(b"\r\n"):
            rest = rest[2:]
    return out
