"""Serve deployment for the inference engine: POST /v1/generate.

Each replica owns one InferenceEngine (its own cache pool + decode
loop); Serve's router spreads requests over replicas and the
AutoscalingConfig grows/shrinks the replica set on per-replica in-flight
load — which for this deployment IS the engine queue depth, since every
in-flight request is either holding a decode slot or parked in the
engine's admission queue.  `max_concurrent_queries` is set well above
`max_slots` so the engine (not the router) does the queueing and the
continuous-batching loop sees the real backlog.

Request JSON (POST /v1/generate — any /v1/* path routes here, the
deployment is named "v1"):

    {"prompt": [1, 2, 3] | "text",     # token ids, or a string encoded
                                       #   bytewise modulo the vocab
     "max_tokens": 16,                 # default engine_cfg.default_max_new
     "temperature": 0.0,               # 0 = greedy
     "seed": 0,
     "stream": false}

Non-streaming replies {"tokens": [...], "n": n, "ttft_s": ..., ...};
``stream: true`` returns a generator the asyncio proxy flushes as
chunked transfer-encoding — one JSON document per chunk, each carrying
one token, then a final ``{"done": true, ...}`` chunk.
"""

from __future__ import annotations

import json
from typing import Optional, Sequence, Union

import jax

from ray_tpu.inference.engine import EngineConfig, InferenceEngine
from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.serve.deployment import (AutoscalingConfig, Deployment,
                                      DeploymentOptions)

DEFAULT_ROUTE = "v1"


def encode_prompt(prompt: Union[str, Sequence[int]],
                  vocab_size: int) -> list[int]:
    """Token ids pass through; strings encode bytewise modulo the vocab
    (the repo ships no tokenizer — this keeps the HTTP surface usable
    end-to-end and is trivially reversible for vocab >= 256)."""
    if isinstance(prompt, str):
        if not prompt:
            raise ValueError("empty prompt")
        return [b % vocab_size for b in prompt.encode("utf-8")]
    return [int(t) for t in prompt]


class GPTServer:
    """Replica body: one engine per replica.

    Params are derived from ``seed`` at replica init (deterministic
    across replicas, so any replica answers any request identically
    under greedy decoding), or passed in directly for in-process use.
    """

    def __init__(self, cfg: Optional[GPTConfig] = None,
                 engine_cfg: Optional[EngineConfig] = None,
                 seed: int = 0, params=None,
                 engine_name: Optional[str] = None):
        self.cfg = cfg or GPTConfig.tiny()
        if params is None:
            params = gpt.init_params(self.cfg, jax.random.PRNGKey(seed))
        self.engine = InferenceEngine(params, self.cfg,
                                      engine_cfg or EngineConfig(),
                                      name=engine_name)

    def __call__(self, req):
        if not isinstance(req, dict):
            raise ValueError(
                "expected a JSON object body, e.g. "
                '{"prompt": [1, 2, 3], "max_tokens": 16}')
        if "prompt" not in req:
            raise ValueError('missing required field "prompt"')
        prompt = encode_prompt(req["prompt"], self.cfg.vocab_size)
        handle = self.engine.submit(
            prompt,
            max_new=req.get("max_tokens"),
            temperature=float(req.get("temperature", 0.0)),
            seed=int(req.get("seed", 0)))
        if req.get("stream"):
            return self._stream(handle)
        try:
            toks = handle.result(timeout=float(req.get("timeout", 120.0)))
        except TimeoutError:
            # shed the abandoned generation: nobody will read it, so it
            # must not keep holding a decode slot against live requests
            handle.cancel()
            raise
        return {
            "tokens": toks,
            "n": len(toks),
            "ttft_s": (handle.first_token_s or 0) - handle.created_s,
            "latency_s": (handle.finished_s or 0) - handle.created_s,
        }

    @staticmethod
    def _stream(handle):
        def gen():
            i = 0
            try:
                for tok in handle.stream():
                    yield {"token": int(tok), "index": i}
                    i += 1
                yield {"done": True, "n": i,
                       "latency_s": (handle.finished_s or 0)
                       - handle.created_s}
            finally:
                # client disconnect mid-stream closes the generator
                # (GeneratorExit lands here): stop decoding for nobody
                if not handle.done:
                    handle.cancel()
        return gen()

    # surfaced for tests / the metrics endpoint via the engine registry
    def engine_stats(self):
        return self.engine.stats()

    def health(self):
        return True

    def teardown(self):
        """Replica teardown hook (DeploymentState.scale_to): stop the
        engine loop so a scaled-down replica releases its KV pool and
        thread instead of leaking them."""
        self.engine.shutdown(timeout=2.0)

    def __del__(self):   # best-effort: teardown() is the real path
        try:
            self.engine.shutdown(timeout=0.5)
        except Exception:
            pass


def build_gpt_deployment(*, name: str = DEFAULT_ROUTE,
                         cfg: Optional[GPTConfig] = None,
                         engine_cfg: Optional[EngineConfig] = None,
                         seed: int = 0,
                         num_replicas: int = 1,
                         max_concurrent_queries: int = 64,
                         autoscaling: Optional[AutoscalingConfig] = None,
                         params=None) -> Deployment:
    """A ready-to-``serve.run`` deployment wrapping GPTServer.  Route is
    /<name>/... — the default name "v1" makes POST /v1/generate work.

    Pass ``autoscaling`` (e.g. AutoscalingConfig(min_replicas=1,
    max_replicas=4, target_ongoing_requests=max_slots)) to scale the
    replica set on queue depth; each new replica brings its own engine
    and cache pool.
    """
    return Deployment(
        GPTServer,
        DeploymentOptions(name=name, num_replicas=num_replicas,
                          max_concurrent_queries=max_concurrent_queries,
                          autoscaling=autoscaling),
        init_args=(),
        init_kwargs=dict(cfg=cfg, engine_cfg=engine_cfg, seed=seed,
                         params=params))


def parse_stream_chunks(raw: bytes) -> list[dict]:
    """Decode the chunked-transfer JSON documents a streamed /v1/generate
    response carries (helper for clients and tests: one dict per chunk,
    in arrival order)."""
    out = []
    rest = raw
    while rest:
        head, _, rest = rest.partition(b"\r\n")
        if not head:
            continue
        n = int(head, 16)
        if n == 0:
            break
        out.append(json.loads(rest[:n]))
        rest = rest[n:]
        if rest.startswith(b"\r\n"):
            rest = rest[2:]
    return out
