"""ray_tpu.inference: continuous-batching LLM inference under Serve.

The "millions of users" leg of the north star (ROADMAP item 2): an
end-to-end inference product over the sharded GPT —

  * decode.py  — compiled decode programs: block-table paged decode
                 step + chunked prefill (production), slot step + full
                 prefill via the ordinary training forward
                 (``gpt.forward(return_kv=True)`` — also the paged
                 cold-start path), speculative-decoding bodies (widened
                 verify step, truncated-layer draft step, host-side
                 n-gram drafter), all compiled once per geometry.  With
                 a mesh the paged bodies run tensor-parallel (pools
                 heads-sharded, one collective per layer) and MoE
                 configs decode via the training forward's expert
                 dispatch.
  * cache.py   — BlockPool (refcounted token blocks, copy-on-write
                 tails, scratch-block scatter discipline) + RadixIndex
                 (prefix reuse trie, LRU eviction); KVCacheManager is
                 the legacy slot pool (A/B baseline).
  * engine.py  — the Orca-style iteration-level scheduler over the
                 paged cache: block-budget admission with prefix-hit
                 credit, occupancy-aware chunked prefill, block-
                 pressure preemption, streams tokens per request.
  * serving.py — the Serve deployment (POST /v1/generate, JSON +
                 chunked token streaming, replica autoscaling, block/
                 prefix gauges for the fleet router).

Quick start::

    from ray_tpu import serve
    from ray_tpu.inference import build_gpt_deployment
    serve.run(build_gpt_deployment(), use_actors=False, http=True)
    # curl -d '{"prompt": [1,2,3], "max_tokens": 8}' \
    #      http://127.0.0.1:<port>/v1/generate

Benchmark receipt: benchmarks/serve_bench.py → SERVE_r17.json
(paged+prefix vs the r14 slot engine, continuous batching vs naive
sequential, AND tp-sharded vs single-device decode, all same-box
same-run A/B).
"""

from __future__ import annotations

from ray_tpu.inference.cache import BlockPool, KVCacheManager, RadixIndex
from ray_tpu.inference.decode import (MoEDecodeUnsupported,
                                      SpeculationUnsupported,
                                      make_chunk_prefill_fn,
                                      make_decode_step,
                                      make_paged_decode_step,
                                      make_paged_draft_step,
                                      make_prefill_fn,
                                      make_spec_verify_step,
                                      ngram_propose)
from ray_tpu.inference.engine import (PRIORITY_BATCH, PRIORITY_INTERACTIVE,
                                      EngineConfig, EngineDrainingError,
                                      EngineStoppedError,
                                      GenerationRequest, InferenceEngine,
                                      metrics_snapshot)
from ray_tpu.inference.serving import (GPTServer, build_gpt_deployment,
                                       encode_prompt, parse_stream_chunks)

__all__ = [
    "BlockPool", "KVCacheManager", "RadixIndex",
    "MoEDecodeUnsupported", "SpeculationUnsupported",
    "make_chunk_prefill_fn", "make_decode_step",
    "make_paged_decode_step", "make_paged_draft_step", "make_prefill_fn",
    "make_spec_verify_step", "ngram_propose",
    "EngineConfig", "EngineDrainingError", "EngineStoppedError",
    "GenerationRequest",
    "InferenceEngine", "PRIORITY_BATCH", "PRIORITY_INTERACTIVE",
    "metrics_snapshot", "GPTServer", "build_gpt_deployment",
    "encode_prompt", "parse_stream_chunks",
]

from ray_tpu import usage_stats as _usage_stats
_usage_stats.record_library_usage("inference")
