"""ray_tpu.inference: continuous-batching LLM inference under Serve.

The "millions of users" leg of the north star (ROADMAP item 2): an
end-to-end inference product over the sharded GPT —

  * decode.py  — KV-cache'd incremental decode: prefill seeds the cache
                 through the ordinary training forward
                 (``gpt.forward(return_kv=True)``), a compiled-once
                 fixed-width step decodes one token for every slot.
  * cache.py   — KVCacheManager: preallocated slot pool, bounded memory
                 regardless of request mix (vLLM's pool discipline in
                 static-shape jax form).
  * engine.py  — the Orca-style iteration-level scheduler: admits new
                 requests at prefill boundaries mid-decode, evicts on
                 EOS/max-tokens, streams tokens per request.
  * serving.py — the Serve deployment (POST /v1/generate, JSON +
                 chunked token streaming, replica autoscaling).

Quick start::

    from ray_tpu import serve
    from ray_tpu.inference import build_gpt_deployment
    serve.run(build_gpt_deployment(), use_actors=False, http=True)
    # curl -d '{"prompt": [1,2,3], "max_tokens": 8}' \
    #      http://127.0.0.1:<port>/v1/generate

Benchmark receipt: benchmarks/serve_bench.py → SERVE_r10.json
(continuous batching vs naive sequential A/B on the same box/run).
"""

from __future__ import annotations

from ray_tpu.inference.cache import KVCacheManager
from ray_tpu.inference.decode import make_decode_step, make_prefill_fn
from ray_tpu.inference.engine import (PRIORITY_BATCH, PRIORITY_INTERACTIVE,
                                      EngineConfig, EngineDrainingError,
                                      EngineStoppedError,
                                      GenerationRequest, InferenceEngine,
                                      metrics_snapshot)
from ray_tpu.inference.serving import (GPTServer, build_gpt_deployment,
                                       encode_prompt, parse_stream_chunks)

__all__ = [
    "KVCacheManager", "make_decode_step", "make_prefill_fn",
    "EngineConfig", "EngineDrainingError", "EngineStoppedError",
    "GenerationRequest",
    "InferenceEngine", "PRIORITY_BATCH", "PRIORITY_INTERACTIVE",
    "metrics_snapshot", "GPTServer", "build_gpt_deployment",
    "encode_prompt", "parse_stream_chunks",
]

from ray_tpu import usage_stats as _usage_stats
_usage_stats.record_library_usage("inference")
