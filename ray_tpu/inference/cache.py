"""KV-cache pools: the paged block pool (production) and the legacy
slot pool (A/B baseline).

vLLM's insight (PagedAttention) is that serving memory must be bounded
by a PREALLOCATED pool handed out in fixed-size units and reclaimed on
sequence exit — never grown per request.  Two unit granularities live
here:

  * ``BlockPool`` — the paged pool: fixed-size TOKEN BLOCKS
    (``[n_layers, n_blocks, n_heads, block_size, head_dim]`` ×2), a
    per-request BLOCK TABLE mapping sequence positions to blocks, and
    per-block REFCOUNTS so blocks are shared across requests (prefix
    reuse) with copy-on-write on a shared partially-filled tail.  The
    decode step stays compiled-once because the table width and batch
    width are static; the price is one gather per step (the trade the
    slot design deferred — now paid, because block granularity lets
    long and short sequences share one pool with near-zero waste).
    Block id 0 is a reserved SCRATCH block: masked rows and
    out-of-range writes are redirected there so the compiled step never
    needs a conditional scatter.
  * ``KVCacheManager`` — the round-10 slot pool (one ``[max_seq]``
    stripe per sequence).  Kept as the ``paged=False`` engine mode so
    the serving benchmark can A/B the paged path against the exact
    engine that shipped in SERVE_r10/r14.

``RadixIndex`` is the prefix cache over the block pool: a trie keyed on
block-sized token chunks (plus partial-tail leaves), so a new request
whose prompt head matches a cached prefix ADOPTS those blocks by
refcount instead of re-running prefill (SGLang's RadixAttention shape).
Unreferenced cached prefixes are LRU-evicted under pool pressure.

Array updates go through jitted helpers (slot/block write, block copy,
pool swap) so the engine loop never materializes a second full pool on
the host.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules, spec_for


@partial(jax.jit, donate_argnums=(0,))
def _write_slot(pool: jax.Array, slot: jax.Array, new: jax.Array):
    """pool [L, B, h, S, hd] <- new [L, h, S, hd] at slot (dynamic)."""
    return pool.at[:, slot].set(new.astype(pool.dtype))


class KVCacheManager:
    """Owns the preallocated K/V pool and the slot free-list.

    Thread contract: `alloc`/`free`/array swaps happen on the engine
    loop thread; `stats()` may be read from any thread (metrics export)
    — the lock only guards the free-list and counters.
    """

    def __init__(self, cfg: GPTConfig, n_slots: int,
                 max_seq: Optional[int] = None,
                 dtype=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_seq = int(max_seq or cfg.max_seq)
        if self.max_seq > cfg.max_seq:
            raise ValueError(
                f"cache max_seq {self.max_seq} exceeds model max_seq "
                f"{cfg.max_seq} (wpe table bound)")
        self.dtype = dtype or cfg.dtype
        shape = (cfg.n_layers, n_slots, cfg.n_heads, self.max_seq,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self._lock = threading.Lock()
        self._free = list(range(n_slots - 1, -1, -1))   # pop() -> slot 0 first
        self._allocated: set[int] = set()

    # ------------------------------------------------------------- slots

    def alloc(self) -> Optional[int]:
        """Hand out a slot, or None when the pool is exhausted (caller
        queues the request — memory never grows)."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._allocated.add(slot)
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot not in self._allocated:
                raise ValueError(f"slot {slot} is not allocated "
                                 "(double free or never alloc'd)")
            self._allocated.remove(slot)
            self._free.append(slot)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._allocated)

    # ------------------------------------------------------------- arrays

    def write_prefill(self, slot: int, k_new: jax.Array,
                      v_new: jax.Array) -> None:
        """Seed a slot from prefill output ([L, h, S, hd] each; S may be
        shorter than the pool stripe — zero-padded on the right, the
        padded tail is masked by kv_lengths and overwritten by decode)."""
        s = k_new.shape[2]
        if s < self.max_seq:
            pad = [(0, 0), (0, 0), (0, self.max_seq - s), (0, 0)]
            k_new = jnp.pad(k_new, pad)
            v_new = jnp.pad(v_new, pad)
        self.k = _write_slot(self.k, jnp.int32(slot), k_new)
        self.v = _write_slot(self.v, jnp.int32(slot), v_new)

    def swap(self, k: jax.Array, v: jax.Array) -> None:
        """Install the decode step's updated pool arrays."""
        self.k, self.v = k, v

    def reset_arrays(self) -> None:
        """Reallocate the pool.  Needed after a FAILED decode step: the
        step donates the cache buffers (donate_argnums), so an exception
        mid-step can leave self.k/v pointing at invalidated storage —
        every later use would raise 'buffer was donated'.  All in-flight
        requests are failed by the caller, so zeros are the right
        content."""
        shape = self.k.shape
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    # ------------------------------------------------------------- stats

    def bytes_total(self) -> int:
        itemsize = np.dtype(
            jnp.zeros((), self.dtype).dtype).itemsize
        return 2 * int(np.prod(self.k.shape)) * itemsize

    def stats(self) -> dict:
        with self._lock:
            active = len(self._allocated)
        return {
            "n_slots": self.n_slots,
            "active_slots": active,
            "free_slots": self.n_slots - active,
            "max_seq": self.max_seq,
            "bytes_total": self.bytes_total(),
        }


# ---------------------------------------------------------------------------
# paged pool


@partial(jax.jit, donate_argnums=(0,))
def _copy_block(pool: jax.Array, src: jax.Array, dst: jax.Array):
    """pool [L, N, h, bs, hd] <- pool[:, src] at dst (copy-on-write)."""
    return pool.at[:, dst].set(pool[:, src])


@partial(jax.jit, donate_argnums=(0,))
def _write_blocks(pool: jax.Array, table: jax.Array, new: jax.Array):
    """pool [L, N, h, bs, hd] <- new [L, h, T*bs, hd] scattered through
    table [T] (position p lands at (table[p//bs], p%bs)).  Duplicate
    scratch entries collide harmlessly — their content is masked."""
    L, _, h, bs, hd = pool.shape
    T = table.shape[0]
    n = new.reshape(L, h, T, bs, hd).transpose(0, 2, 1, 3, 4)
    return pool.at[:, table].set(n.astype(pool.dtype))


@jax.jit
def _gather_blocks(pool_k: jax.Array, pool_v: jax.Array,
                   table: jax.Array):
    """Both pools' block chains in ONE fused call — the eager two-step
    (k then v, each its own dispatch + device_get) dominated prefix
    extraction latency, not the bytes."""
    return pool_k[:, table], pool_v[:, table]


@partial(jax.jit, donate_argnums=(0, 1))
def _install_blocks(pool_k: jax.Array, pool_v: jax.Array,
                    table: jax.Array, new_k: jax.Array,
                    new_v: jax.Array):
    """pools [L, N, h, bs, hd] <- new [L, T, h, bs, hd] at table [T]:
    the adopted-prefix scatter, taking the transfer payload's layout
    directly (no eager transpose/reshape copies) and landing both
    pools in ONE dispatch.  The caller owns ``table``'s ids
    exclusively (refcount 1, freshly alloc'd), so no CoW is needed."""
    return (pool_k.at[:, table].set(new_k.astype(pool_k.dtype)),
            pool_v.at[:, table].set(new_v.astype(pool_v.dtype)))


class BlockPool:
    """Refcounted fixed-size token-block pool (the paged KV cache).

    Arrays are ``[n_layers, n_blocks + 1, n_heads, block_size,
    head_dim]`` ×2 — index 0 is the reserved scratch block (never
    allocated; inactive/out-of-range writes in the compiled step are
    redirected there), usable blocks are ids ``1..n_blocks``.

    Reference rules: ``alloc()`` returns a block with refcount 1;
    every additional holder (a sharing request, the prefix trie)
    ``incref``s; ``decref`` frees the block back to the pool when the
    count reaches 0.  A holder about to WRITE a block must own it
    exclusively (refcount 1) — otherwise copy-on-write first
    (``copy_block`` into a fresh block, drop the shared reference).

    Thread contract mirrors KVCacheManager: alloc/incref/decref/array
    swaps happen on the engine loop thread; ``stats()`` may be read
    from any thread (the lock only guards the free list + refcounts).

    With a ``mesh``, the pool arrays are sharded over the HEADS dim
    (decode.POOL_AXES — Megatron-style tensor parallelism): every
    device holds all ``n_blocks + 1`` blocks with ``n_heads / tp`` of
    each block's heads, so block ids, tables, refcounts, the radix trie
    and copy-on-write are shard-oblivious and ``n_blocks`` is both the
    global admission budget AND the per-device block count (per-device
    bytes are ``bytes_total() / tp``).
    """

    def __init__(self, cfg: GPTConfig, n_blocks: int, block_size: int,
                 max_seq: Optional[int] = None, dtype=None, mesh=None,
                 rules: Rules = DEFAULT_LLM_RULES):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        self.cfg = cfg
        self.mesh = mesh
        self.rules = rules
        self.block_size = int(block_size)
        self.max_seq = int(max_seq or cfg.max_seq)
        if self.max_seq > cfg.max_seq:
            raise ValueError(
                f"cache max_seq {self.max_seq} exceeds model max_seq "
                f"{cfg.max_seq} (wpe table bound)")
        # block-table width: enough blocks to cover one max_seq sequence
        self.blocks_per_seq = -(-self.max_seq // self.block_size)
        if n_blocks < self.blocks_per_seq:
            raise ValueError(
                f"n_blocks {n_blocks} cannot hold one max_seq={self.max_seq} "
                f"sequence ({self.blocks_per_seq} blocks of {block_size})")
        self.n_blocks = int(n_blocks)             # usable (excludes scratch)
        self.dtype = dtype or cfg.dtype
        self._shape = (cfg.n_layers, self.n_blocks + 1, cfg.n_heads,
                       self.block_size, cfg.head_dim)
        shards = self.heads_shards
        if cfg.n_heads % shards:
            raise ValueError(
                f"n_heads {cfg.n_heads} is not divisible by the heads "
                f"(tp) shard count {shards} of mesh "
                f"{dict(zip(mesh.axis_names, mesh.devices.shape))} — "
                f"the pool shards the heads dim evenly per device")
        self.k = self._zeros()
        self.v = self._zeros()
        self._lock = threading.Lock()
        # pop() -> block 1 first; id 0 (scratch) is never in the list
        self._free = list(range(self.n_blocks, 0, -1))
        self._rc = [0] * (self.n_blocks + 1)
        # bumped by every reset(): block ids published before a reset
        # (e.g. to the cluster prefix directory) are fenced by this —
        # a recovered pool's old ids must never be served remotely
        self.generation = 0

    @property
    def heads_shards(self) -> int:
        """Number of shards the pool's heads dim is split into (1 when
        unmeshed) — the ``tp`` degree of the serving hot path."""
        if self.mesh is None:
            return 1
        spec = self._pool_spec()[2]
        if spec is None:
            return 1
        axes = (spec,) if isinstance(spec, str) else spec
        n = 1
        for a in axes:
            n *= dict(zip(self.mesh.axis_names,
                          self.mesh.devices.shape))[a]
        return n

    def _pool_spec(self):
        from ray_tpu.inference.decode import POOL_AXES
        return spec_for(POOL_AXES, self.rules, self.mesh)

    def _zeros(self) -> jax.Array:
        """Allocate one zeroed pool array — heads-sharded across the
        mesh when there is one (allocated shard-local via out_shardings,
        never materialized unsharded), plain jnp.zeros otherwise.  Used
        by __init__ AND reset() so donated-pool recovery reallocates
        every device's shard, not just the addressable default."""
        if self.mesh is None:
            return jnp.zeros(self._shape, self.dtype)
        from jax.sharding import NamedSharding
        sh = NamedSharding(self.mesh, self._pool_spec())
        return jax.jit(partial(jnp.zeros, self._shape, self.dtype),
                       out_shardings=sh)()

    # ------------------------------------------------------------- blocks

    def alloc(self) -> Optional[int]:
        """Hand out a block (refcount 1), or None when the pool is dry
        (caller evicts cached prefixes, preempts, or queues)."""
        with self._lock:
            if not self._free:
                return None
            bid = self._free.pop()
            self._rc[bid] = 1
            return bid

    def incref(self, bid: int) -> None:
        with self._lock:
            if self._rc[bid] < 1:
                raise ValueError(f"block {bid} is not allocated")
            self._rc[bid] += 1

    def decref(self, bid: int) -> int:
        """Drop one reference; frees the block at zero.  Returns the
        remaining count."""
        with self._lock:
            if self._rc[bid] < 1:
                raise ValueError(f"block {bid} is not allocated "
                                 "(double free or never alloc'd)")
            self._rc[bid] -= 1
            rc = self._rc[bid]
            if rc == 0:
                self._free.append(bid)
            return rc

    def refcount(self, bid: int) -> int:
        with self._lock:
            return self._rc[bid]

    def release_tail(self, blocks: list, keep: int) -> int:
        """Multi-token ROLLBACK (speculative decode): drop and decref
        the chain's blocks past the first ``keep`` — the refund of a
        block charge taken up front for drafted tokens the verify pass
        rejected.  ``blocks`` is truncated in place (the caller's
        row-chain list stays the single source of truth, so a
        preemption racing in later still releases exactly what the row
        holds).  Rolled-back blocks may contain rejected lanes' K/V —
        garbage beyond the row's committed length, masked everywhere
        and freed here, never leaked.  Returns the number released."""
        keep = max(int(keep), 0)
        dropped = 0
        while len(blocks) > keep:
            self.decref(blocks.pop())
            dropped += 1
        return dropped

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_used(self) -> int:
        with self._lock:
            return self.n_blocks - len(self._free)

    # ------------------------------------------------------------- arrays

    def copy_block(self, src: int, dst: int) -> None:
        """Copy-on-write: duplicate src's K/V into dst (both pools)."""
        s, d = jnp.int32(src), jnp.int32(dst)
        self.k = _copy_block(self.k, s, d)
        self.v = _copy_block(self.v, s, d)

    def read_blocks(self, ids) -> tuple:
        """Gather a block chain's K/V to host arrays — the EXPORT side
        of replica→replica prefix transfer.  Returns ``(k, v)`` of shape
        ``[L, T, h, bs, hd]`` each (T = len(ids)), fully replicated
        host-side so the bytes can ride the object plane regardless of
        the holder's mesh layout."""
        t = jnp.asarray(list(ids), jnp.int32)
        k, v = jax.device_get(_gather_blocks(self.k, self.v, t))
        return np.asarray(k), np.asarray(v)

    def write_blocks_at(self, ids, k_new, v_new) -> None:
        """Scatter fetched block K/V (``read_blocks`` layout,
        ``[L, T, h, bs, hd]``) into freshly-allocated local blocks —
        the INSTALL side of prefix adoption.  The caller owns ``ids``
        exclusively (refcount 1, just alloc'd), so no CoW is needed;
        with a mesh the ``.at[].set`` lands sharded through the pool's
        own sharding."""
        t = jnp.asarray(list(ids), jnp.int32)
        L, T = self.k.shape[0], t.shape[0]
        h, bs, hd = self.k.shape[2], self.k.shape[3], self.k.shape[4]
        k_new = jnp.asarray(k_new, self.dtype).reshape(L, T, h, bs, hd)
        v_new = jnp.asarray(v_new, self.dtype).reshape(L, T, h, bs, hd)
        self.k, self.v = _install_blocks(self.k, self.v, t,
                                         k_new, v_new)

    def write_prefill(self, table, k_new: jax.Array,
                      v_new: jax.Array) -> None:
        """Seed a request's blocks from a FULL prefill ([L, h, S, hd]
        each — the r10 training-forward prefill): the whole padded
        sequence scatters through the block table in one jitted call.
        S may be shorter than the table span (zero-padded right);
        unowned table entries point at the scratch block, whose garbage
        the kv-length masks hide."""
        span = self.blocks_per_seq * self.block_size
        s = k_new.shape[2]
        if s < span:
            pad = [(0, 0), (0, 0), (0, span - s), (0, 0)]
            k_new = jnp.pad(k_new, pad)
            v_new = jnp.pad(v_new, pad)
        t = jnp.asarray(table, jnp.int32)
        self.k = _write_blocks(self.k, t, k_new)
        self.v = _write_blocks(self.v, t, v_new)

    def swap(self, k: jax.Array, v: jax.Array) -> None:
        """Install the compiled step's updated pool arrays."""
        self.k, self.v = k, v

    def reset(self) -> None:
        """Reallocate the pool and drop every reference.  Needed after a
        FAILED compiled step: chunk-prefill and decode both donate the
        pool buffers, so an exception mid-step can leave self.k/v
        pointing at invalidated storage.  The caller fails all in-flight
        requests AND clears the prefix index (cached prefixes would
        otherwise point at zeroed blocks — silently wrong KV).  With a
        mesh, _zeros reallocates the pool SHARDED, every device's shard
        included — recovery must restore the same layout the compiled
        steps donate-commit into."""
        self.k = self._zeros()
        self.v = self._zeros()
        with self._lock:
            self._free = list(range(self.n_blocks, 0, -1))
            self._rc = [0] * (self.n_blocks + 1)
            self.generation += 1

    # ------------------------------------------------------------- stats

    def bytes_total(self) -> int:
        itemsize = np.dtype(jnp.zeros((), self.dtype).dtype).itemsize
        return 2 * int(np.prod(self.k.shape)) * itemsize

    def stats(self) -> dict:
        with self._lock:
            free = len(self._free)
        shards = self.heads_shards
        return {
            "block_size": self.block_size,
            # blocks are replicated in COUNT across tp shards (heads are
            # what's split), so blocks_total is simultaneously the
            # global admission budget and the per-device block count —
            # both reported so no consumer has to guess which one a
            # gauge means
            "blocks_total": self.n_blocks,
            "blocks_per_device": self.n_blocks,
            "blocks_free": free,
            "blocks_used": self.n_blocks - free,
            "max_seq": self.max_seq,
            "bytes_total": self.bytes_total(),
            "bytes_per_device": self.bytes_total() // shards,
            "tp_shards": shards,
            "generation": self.generation,
        }


# ---------------------------------------------------------------------------
# radix prefix index


class _TrieNode:
    __slots__ = ("key", "block", "n_valid", "children", "parent", "lru")

    def __init__(self, key, block, n_valid, parent):
        self.key = key            # tuple of tokens (len == block_size for
        #                           interior/full nodes, < for tail leaves)
        self.block = block        # pool block id holding these tokens' KV
        self.n_valid = n_valid    # valid token count in the block
        self.children: dict = {}
        self.parent = parent
        self.lru = 0


class RadixIndex:
    """Trie over cached prompt prefixes, keyed on block-sized token
    chunks; holds one pool reference per cached block.

    * ``insert(tokens, block_ids)`` — cache a finished/preempted
      request's prefix chain: full blocks become interior nodes, a
      partial tail becomes a leaf (matched only when its whole content
      is a prefix of a later prompt — the shared-prompt-head case).
      Already-cached chunks dedupe to the existing node (the caller's
      duplicate block is simply not retained).
    * ``match(prompt)`` — longest cached chain that is a prefix of the
      prompt, CAPPED at ``len(prompt) - 1`` tokens so at least one
      prompt token always runs prefill (its logits produce the first
      sampled token).  Matched blocks are increfed for the caller.
    * ``evict(n)`` — LRU eviction of UNREFERENCED leaves (pool refcount
      1, i.e. only the trie holds the block); interior nodes become
      evictable once their subtree is gone.

    Single-threaded by design: called only from the engine loop thread
    (stats excepted, guarded by the pool's lock via refcounts).
    """

    def __init__(self, pool: BlockPool):
        self.pool = pool
        self.bs = pool.block_size
        self.root = _TrieNode((), 0, 0, None)
        self._clock = 0
        self._nodes = 0
        # cumulative token counters (engine folds into stats)
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.evicted_blocks = 0

    def _touch(self, node: _TrieNode) -> None:
        self._clock += 1
        while node is not None and node is not self.root:
            node.lru = self._clock
            node = node.parent

    @property
    def cached_blocks(self) -> int:
        return self._nodes

    # -------------------------------------------------------------- match

    def match(self, prompt: np.ndarray) -> tuple:
        """(block_ids, n_tokens): the adopted chain, blocks increfed.
        Caller must decref each id when done (release or CoW)."""
        bs = self.bs
        n = len(prompt)
        self.lookup_tokens += n
        node, ids, matched = self.root, [], 0
        while matched + bs < n:        # full block AND >= 1 token left over
            key = tuple(int(t) for t in prompt[matched:matched + bs])
            child = node.children.get(key)
            if child is None or child.n_valid != bs:
                break
            ids.append(child.block)
            matched += bs
            node = child
        # partial tail leaves: longest one whose WHOLE content prefixes
        # the remaining prompt (still leaving >= 1 token for prefill)
        best = None
        for key, child in node.children.items():
            m = len(key)
            if m >= bs or m >= n - matched:
                continue
            if tuple(int(t) for t in prompt[matched:matched + m]) != key:
                continue
            if best is None or m > len(best.key):
                best = child
        if best is not None:
            ids.append(best.block)
            matched += len(best.key)
            node = best
        for bid in ids:
            self.pool.incref(bid)
        if node is not self.root:
            self._touch(node)
        self.hit_tokens += matched
        return ids, matched

    # ------------------------------------------------------------- insert

    def insert(self, tokens: np.ndarray, block_ids: list) -> None:
        """Cache the chain for ``tokens`` (the request's clean KV prefix)
        backed by ``block_ids`` (the request's table, in order).  Kept
        blocks gain a trie reference; chunks already cached dedupe to
        the existing node and the caller's copy is not retained."""
        bs = self.bs
        n = len(tokens)
        node = self.root
        for i in range(n // bs):
            key = tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
            child = node.children.get(key)
            if child is None:
                bid = block_ids[i]
                child = _TrieNode(key, bid, bs, node)
                node.children[key] = child
                self.pool.incref(bid)
                self._nodes += 1
            node = child
        j = n % bs
        if j:
            key = tuple(int(t) for t in tokens[n - j:])
            if key not in node.children:
                bid = block_ids[n // bs]
                leaf = _TrieNode(key, bid, j, node)
                node.children[key] = leaf
                self.pool.incref(bid)
                self._nodes += 1
                node = leaf
        self._touch(node)

    # ------------------------------------------------------------ evict

    def _leaves(self) -> list:
        out, stack = [], [self.root]
        while stack:
            node = stack.pop()
            kids = list(node.children.values())
            if not kids and node is not self.root:
                out.append(node)
            stack.extend(kids)
        return out

    def evict(self, n: int) -> int:
        """Free up to ``n`` blocks by dropping unreferenced cached
        prefixes, LRU-first, leaves-up.  Returns blocks actually freed
        (may be < n when everything left is referenced by a request).

        ONE trie walk per call seeds an LRU heap of evictable leaves;
        evicting a leaf pushes its parent when that exposes it — so a
        multi-block eviction is O(nodes + freed·log) instead of one
        full walk (plus a refcount lock round-trip per node) per freed
        block, which mattered: admission/growth pressure calls this
        from the decode hot path."""
        import heapq
        freed = 0
        heap = [(leaf.lru, id(leaf), leaf) for leaf in self._leaves()
                if self.pool.refcount(leaf.block) == 1]
        heapq.heapify(heap)
        while heap and freed < n:
            _, _, node = heapq.heappop(heap)
            # a heap entry may be stale (re-referenced since the walk)
            if (node.children
                    or node.parent.children.get(node.key) is not node
                    or self.pool.refcount(node.block) != 1):
                continue
            del node.parent.children[node.key]
            self.pool.decref(node.block)
            self._nodes -= 1
            freed += 1
            self.evicted_blocks += 1
            p = node.parent
            if (p is not self.root and not p.children
                    and self.pool.refcount(p.block) == 1):
                heapq.heappush(heap, (p.lru, id(p), p))
        return freed

    def clear(self) -> None:
        """Drop the whole index WITHOUT touching pool refcounts — used
        only after BlockPool.reset() (which already zeroed them)."""
        self.root = _TrieNode((), 0, 0, None)
        self._nodes = 0
