"""KV-cache pool: preallocated, slot-granular, bounded.

vLLM's insight (PagedAttention) is that serving memory must be bounded by
a PREALLOCATED pool handed out in fixed-size units and reclaimed on
sequence exit — never grown per request.  Under jax/pjit the unit has to
keep the decode step's shapes static so it compiles exactly once, so the
unit here is a SLOT: one `[max_seq]` stripe of the cache per admitted
sequence (the block-granular refinement would trade the static shape for
a gather per step; see ARCHITECTURE.md "Inference engine" for the
trade).  The pool is two arrays

    k, v : [n_layers, n_slots, n_heads, max_seq, head_dim]

allocated once at engine construction.  `alloc()` hands a slot out,
`free()` returns it; when every slot is out new requests queue in the
engine instead of growing memory — HBM use is a constant of the engine
config regardless of request mix, which is the property the continuous
batching loop needs to admit mid-decode without OOM risk.

Array updates go through jitted helpers (slot write / pool swap) so the
engine loop never materializes a second full pool on the host.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.models.gpt import GPTConfig


@partial(jax.jit, donate_argnums=(0,))
def _write_slot(pool: jax.Array, slot: jax.Array, new: jax.Array):
    """pool [L, B, h, S, hd] <- new [L, h, S, hd] at slot (dynamic)."""
    return pool.at[:, slot].set(new.astype(pool.dtype))


class KVCacheManager:
    """Owns the preallocated K/V pool and the slot free-list.

    Thread contract: `alloc`/`free`/array swaps happen on the engine
    loop thread; `stats()` may be read from any thread (metrics export)
    — the lock only guards the free-list and counters.
    """

    def __init__(self, cfg: GPTConfig, n_slots: int,
                 max_seq: Optional[int] = None,
                 dtype=None):
        if n_slots < 1:
            raise ValueError(f"n_slots must be >= 1, got {n_slots}")
        self.cfg = cfg
        self.n_slots = n_slots
        self.max_seq = int(max_seq or cfg.max_seq)
        if self.max_seq > cfg.max_seq:
            raise ValueError(
                f"cache max_seq {self.max_seq} exceeds model max_seq "
                f"{cfg.max_seq} (wpe table bound)")
        self.dtype = dtype or cfg.dtype
        shape = (cfg.n_layers, n_slots, cfg.n_heads, self.max_seq,
                 cfg.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)
        self._lock = threading.Lock()
        self._free = list(range(n_slots - 1, -1, -1))   # pop() -> slot 0 first
        self._allocated: set[int] = set()

    # ------------------------------------------------------------- slots

    def alloc(self) -> Optional[int]:
        """Hand out a slot, or None when the pool is exhausted (caller
        queues the request — memory never grows)."""
        with self._lock:
            if not self._free:
                return None
            slot = self._free.pop()
            self._allocated.add(slot)
            return slot

    def free(self, slot: int) -> None:
        with self._lock:
            if slot not in self._allocated:
                raise ValueError(f"slot {slot} is not allocated "
                                 "(double free or never alloc'd)")
            self._allocated.remove(slot)
            self._free.append(slot)

    @property
    def n_free(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def n_active(self) -> int:
        with self._lock:
            return len(self._allocated)

    # ------------------------------------------------------------- arrays

    def write_prefill(self, slot: int, k_new: jax.Array,
                      v_new: jax.Array) -> None:
        """Seed a slot from prefill output ([L, h, S, hd] each; S may be
        shorter than the pool stripe — zero-padded on the right, the
        padded tail is masked by kv_lengths and overwritten by decode)."""
        s = k_new.shape[2]
        if s < self.max_seq:
            pad = [(0, 0), (0, 0), (0, self.max_seq - s), (0, 0)]
            k_new = jnp.pad(k_new, pad)
            v_new = jnp.pad(v_new, pad)
        self.k = _write_slot(self.k, jnp.int32(slot), k_new)
        self.v = _write_slot(self.v, jnp.int32(slot), v_new)

    def swap(self, k: jax.Array, v: jax.Array) -> None:
        """Install the decode step's updated pool arrays."""
        self.k, self.v = k, v

    def reset_arrays(self) -> None:
        """Reallocate the pool.  Needed after a FAILED decode step: the
        step donates the cache buffers (donate_argnums), so an exception
        mid-step can leave self.k/v pointing at invalidated storage —
        every later use would raise 'buffer was donated'.  All in-flight
        requests are failed by the caller, so zeros are the right
        content."""
        shape = self.k.shape
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    # ------------------------------------------------------------- stats

    def bytes_total(self) -> int:
        itemsize = np.dtype(
            jnp.zeros((), self.dtype).dtype).itemsize
        return 2 * int(np.prod(self.k.shape)) * itemsize

    def stats(self) -> dict:
        with self._lock:
            active = len(self._allocated)
        return {
            "n_slots": self.n_slots,
            "active_slots": active,
            "free_slots": self.n_slots - active,
            "max_seq": self.max_seq,
            "bytes_total": self.bytes_total(),
        }
