"""Continuous-batching inference engine (Orca-style iteration-level
scheduling over a fixed decode-batch width) with a PAGED KV cache.

One background loop owns the model state and runs one compiled decode
step per iteration over ALL rows at once.  Between steps — the prefill
boundary — it admits waiting requests, advances prefills, and evicts
finished requests (EOS / max-tokens).  Requests therefore join and
leave MID-DECODE of their neighbors: a long generation never blocks a
short one behind it, and the decode batch stays as full as the offered
load allows — the throughput lever the naive sequential baseline lacks
(benchmarks/serve_bench.py is the A/B receipt).

The default cache is the paged BlockPool (``EngineConfig.paged``):

  * admission is BLOCK-BUDGET accounting, not slot counting — a request
    is admitted when a decode row is free AND the pool can cover its
    prompt after prefix-hit credit (LRU-evicting unreferenced cached
    prefixes under pressure), so short and long sequences share one
    pool with near-zero waste and peak concurrency is bounded by real
    token usage, not worst-case stripes.
  * a radix prefix index (cache.RadixIndex) lets a request whose prompt
    head matches a cached prefix ADOPT those blocks by refcount instead
    of re-running prefill; finished/preempted requests donate their
    clean KV chains back to the index.
  * prefill runs in fixed-width CHUNKS interleaved with decode
    iterations, occupancy-aware (one chunk per pass at healthy decode
    occupancy — bounded stall; batch-fill below it) and shortest-
    remaining-first — a long prompt no longer stalls neighbors' token
    cadence for its whole prefill, and cold duplicates of a shared
    head serialize so one representative publishes for the rest.
  * decode-time block growth that finds the pool dry first evicts
    cached prefixes, then PREEMPTS the youngest lowest-priority request
    (its blocks are donated to the prefix index and it re-queues; on
    re-admission its prompt includes every token already emitted, so
    the stream continues exactly — deterministic for greedy, and
    temperature sampling's rng state lives host-side in the request).

  * SPECULATIVE DECODING (``EngineConfig.speculate``): a drafter
    proposes up to ``speculate_k`` tokens per greedy row per pass — the
    host-side n-gram/prompt-lookup drafter ("ngram") or the
    truncated-layer self-drafter ("self") — and ONE widened verify step
    scores every row's window at once (decode.make_spec_verify_step).
    Greedy accept/reject against the verify argmaxes is token-EXACT, so
    the full-recompute oracle gates it like plain decode; the block
    budget is charged up front for drafted positions (alloc/prefix-
    evict only — hoped-for tokens never preempt a neighbor) and the
    rejected tail's charge rolls back after the pass.  A preempted row
    refunds any speculative charge automatically: granted blocks live
    in the row chain, and preemption releases the chain.

``paged=False`` keeps the round-10/14 slot engine (one ``[max_seq]``
stripe per request) as the same-run A/B baseline.

Tokens stream out per request as they are sampled: GenerationRequest is
a tiny condition-variable mailbox whose ``stream()`` generator the serve
layer turns into chunked transfer-encoding.  All waits are bounded
condition waits (no bare ``Event.wait()`` / ``time.sleep`` polling — the
control-plane lint's blocking rules are the house style even off the
node event loop).

Sampling runs on the host via models.gpt.sample_token — the SAME
function the full-recompute oracle uses, so greedy decode is
token-identical by construction (asserted in tests).  Per-request
temperature/rng stay per-request because sampling is outside the
compiled step; logits [n_slots, vocab] is a small transfer.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.inference.cache import BlockPool, KVCacheManager, RadixIndex
from ray_tpu.inference.decode import (SpeculationUnsupported,
                                      make_chunk_prefill_fn,
                                      make_decode_step,
                                      make_paged_decode_step,
                                      make_paged_draft_step,
                                      make_prefill_fn,
                                      make_spec_verify_step,
                                      ngram_propose)
from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.sharding import (DEFAULT_LLM_RULES, Rules,
                                       tree_shardings)


@dataclass
class EngineConfig:
    """Engine knobs.  ``max_slots`` is the decode-batch width (the
    concurrency cap); memory is ``n_blocks`` × ``kv_block_size`` tokens
    when paged (decoupled from the row count — the mixed-length sharing
    win), or ``max_slots`` × ``max_seq`` tokens in slot mode."""
    max_slots: int = 8
    max_seq: Optional[int] = None        # cache width; None = model max_seq
    eos_token: Optional[int] = None      # None = never stop early
    default_max_new: int = 64
    max_waiting: int = 1024              # admission-queue bound (backpressure)
    idle_wait_s: float = 0.05            # loop park interval when empty
    # ---- paged cache (the production path; False = r14 slot engine,
    # kept in-tree as the benchmark's same-run A/B baseline)
    paged: bool = True
    kv_block_size: int = 16              # tokens per block
    n_blocks: Optional[int] = None       # usable blocks; None = max_slots
    #                                      * ceil(max_seq/block) (same
    #                                      bytes as the slot pool)
    prefill_chunk: int = 32              # chunked-prefill window width
    prefix_cache: bool = True            # radix prefix reuse on/off
    # ---- speculative decoding (draft-then-verify; paged engine only).
    # None = off (the same-run A/B baseline); "ngram" = host-side
    # prompt-lookup drafting against the request's own prompt+history;
    # "self" = truncated-layer self-draft (the first ``draft_layers``
    # layers straight into the head).  Greedy requests emit the EXACT
    # non-speculative token stream (accept/reject is argmax-checked per
    # drafted position); temperature > 0 requests transparently fall
    # back to one token per step — never a silent parity break.
    speculate: Optional[str] = None      # None | "ngram" | "self"
    speculate_k: int = 4                 # drafted tokens per verify pass
    draft_layers: int = 1                # self-drafter depth ("self" mode)


# priority classes + the replica-death/draining errors live in the
# jax-free serve.qos module (the fleet's generic machinery imports them
# from there); re-exported here for the engine's own API surface.
from ray_tpu.serve.qos import (PRIORITY_BATCH,           # noqa: F401
                               PRIORITY_INTERACTIVE, EngineDrainingError,
                               PrefixInstallPressure, PrefixUnavailable,
                               ReplicaDeadError, StalePrefixGeneration,
                               parse_priority)


class EngineStoppedError(ReplicaDeadError):
    """The engine was shut down (replica teardown / chaos kill) with
    this request queued or mid-decode.  A typed subclass so the fleet
    layer can tell a dead replica (retry elsewhere — the generation is
    deterministic from the request) from a request-specific failure
    (do not retry)."""


class GenerationRequest:
    """One in-flight generation: a mailbox the engine appends tokens to
    and consumers drain via ``stream()`` / ``result()``."""

    def __init__(self, req_id: int, prompt: np.ndarray, max_new: int,
                 temperature: float, rng: Optional[jax.Array],
                 priority: int = PRIORITY_BATCH):
        self.id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.priority = priority
        self._rng = rng
        # emitted tokens already folded into ``prompt`` by a preemption
        # (block-pressure requeue): on re-admission the prefill covers
        # prompt+emitted and the stream continues exactly where it was
        self._consumed = 0
        self.tokens: list[int] = []
        self.done = False
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self._cond = threading.Condition()
        self.created_s = time.perf_counter()
        self.created_wall = time.time()   # timeline slices need wall time
        self.first_token_s: Optional[float] = None
        self.finished_s: Optional[float] = None
        # per-token arrival stamps (perf_counter): consecutive diffs are
        # the request's ITLs — the latency series speculation moves
        self.token_times: list[float] = []
        # per-request speculation accounting (accept-rate per stream)
        self.spec_drafted = 0
        self.spec_accepted = 0

    # ---- engine side -----------------------------------------------------

    def _emit(self, token: int) -> None:
        with self._cond:
            now = time.perf_counter()
            if self.first_token_s is None:
                self.first_token_s = now
            self.token_times.append(now)
            self.tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, error: Optional[BaseException] = None) -> None:
        with self._cond:
            self.error = error
            self.done = True
            self.finished_s = time.perf_counter()
            self._cond.notify_all()

    def _next_rng(self) -> Optional[jax.Array]:
        if self._rng is None:
            return None
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- consumer side ---------------------------------------------------

    def cancel(self) -> None:
        """Abandon the request: the engine drops it from the waiting
        queue, or evicts it at the next decode iteration, freeing its
        slot for live work.  Idempotent; a no-op once done."""
        with self._cond:
            self.cancelled = True
            self._cond.notify_all()

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens as they arrive; returns at completion,
        raises the engine-side error if the request failed."""
        i = 0
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        while True:
            with self._cond:
                while len(self.tokens) <= i and not self.done:
                    remain = 0.5
                    if deadline is not None:
                        remain = min(remain, deadline - time.perf_counter())
                        if remain <= 0:
                            raise TimeoutError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                    self._cond.wait(timeout=remain)
                if len(self.tokens) > i:
                    tok = self.tokens[i]
                else:                      # done, mailbox drained
                    if self.error is not None:
                        raise self.error
                    return
            yield tok
            i += 1

    def result(self, timeout: Optional[float] = None) -> list[int]:
        """Block until completion; returns the full generated-token list."""
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while not self.done:
                remain = 0.5
                if deadline is not None:
                    remain = min(remain, deadline - time.perf_counter())
                    if remain <= 0:
                        raise TimeoutError(
                            f"request {self.id} not done within {timeout}s")
                self._cond.wait(timeout=remain)
            if self.error is not None:
                raise self.error
            return list(self.tokens)


# engine registry for /metrics export (weak: an engine dies with its
# replica, the gauge series just disappears — the loop thread also only
# holds its engine weakly, see _engine_loop)
_ENGINES: "weakref.WeakValueDictionary[str, InferenceEngine]" = \
    weakref.WeakValueDictionary()
_engine_seq = itertools.count()
_registry_lock = threading.Lock()


def _engine_loop(ref: "weakref.ref[InferenceEngine]") -> None:
    """Loop-thread driver.  A strong reference exists only DURING a
    pass; between passes the engine is collectable, and a collected
    engine simply ends the thread (its requests are unreachable too,
    short of a consumer-held mailbox, which shutdown()/teardown covers
    for the supported lifecycles)."""
    while True:
        eng = ref()
        if eng is None:
            return
        try:
            alive = eng._loop_pass()
        except BaseException:
            eng._drain_pending()
            raise
        if not alive:
            eng._drain_pending()
            return
        del eng


class InferenceEngine:
    """Continuous-batching engine over one parameter set.

    >>> eng = InferenceEngine(params, cfg, EngineConfig(max_slots=8))
    >>> req = eng.submit([1, 2, 3], max_new=16)
    >>> for tok in req.stream(): ...
    """

    def __init__(self, params, cfg: GPTConfig,
                 engine_cfg: Optional[EngineConfig] = None, *,
                 mesh=None, rules: Rules = DEFAULT_LLM_RULES,
                 name: Optional[str] = None,
                 labels: Optional[dict] = None):
        self.cfg = cfg
        # extra label pairs on this engine's /metrics series (the serve
        # layer sets deployment/replica/model so multi-replica fleets
        # don't collapse into one ambiguous series)
        self.labels = dict(labels) if labels else {}
        self.engine_cfg = engine_cfg or EngineConfig()
        ec = self.engine_cfg
        self._mesh = mesh
        self._rules = rules
        if mesh is not None:
            # shard the weights to match the annotated step bodies
            # (heads/mlp/qkv/vocab over tp per the rules) so the first
            # compiled call doesn't start from fully-replicated params
            self.params = jax.device_put(
                params, tree_shardings(gpt.param_logical_axes(cfg),
                                       rules, mesh))
        else:
            self.params = params
        n = ec.max_slots
        self._paged = bool(ec.paged)
        self._spec = ec.speculate
        if self._spec is not None:
            # the typed capability boundary, at CONSTRUCTION time like
            # MoEDecodeUnsupported: the slot engine is the frozen A/B
            # baseline and grows no speculation path
            if self._spec not in ("ngram", "self"):
                raise ValueError(
                    f"speculate must be None, 'ngram' or 'self', got "
                    f"{self._spec!r}")
            if not self._paged:
                raise SpeculationUnsupported(
                    "speculative decoding needs the paged engine "
                    "(EngineConfig.paged=True); the slot engine is the "
                    "non-speculative A/B baseline")
            if ec.speculate_k < 1:
                raise ValueError(
                    f"speculate_k must be >= 1, got {ec.speculate_k}")
        if self._paged:
            bs = ec.kv_block_size
            per_seq = -(-int(ec.max_seq or cfg.max_seq) // bs)
            n_blocks = ec.n_blocks if ec.n_blocks is not None else n * per_seq
            self.pool = BlockPool(cfg, n_blocks, bs, max_seq=ec.max_seq,
                                  mesh=mesh, rules=rules)
            self.cache = None
            self.max_seq = self.pool.max_seq
            self.trie = (RadixIndex(self.pool) if ec.prefix_cache else None)
            # the full-width prefill stays: a COLD prompt on an idle
            # engine seeds all its blocks from one training-forward call
            # (chunking pays a full-table gather per chunk — it earns
            # its keep on prefix hits and under load, not cold+idle)
            self._prefill = make_prefill_fn(cfg, mesh=mesh, rules=rules)
            self._step = make_paged_decode_step(
                cfg, block_size=bs, n_table=self.pool.blocks_per_seq,
                mesh=mesh, rules=rules)
            self._chunk = make_chunk_prefill_fn(
                cfg, chunk=ec.prefill_chunk, block_size=bs,
                n_table=self.pool.blocks_per_seq, mesh=mesh, rules=rules)
            if self._spec is not None:
                self._verify = make_spec_verify_step(
                    cfg, width=ec.speculate_k + 1, block_size=bs,
                    n_table=self.pool.blocks_per_seq, mesh=mesh,
                    rules=rules)
                # "self" additionally compiles the truncated-layer
                # drafter (raises SpeculationUnsupported on a bad
                # draft_layers — still construction time)
                self._draft = (make_paged_draft_step(
                    cfg, draft_layers=ec.draft_layers,
                    k=ec.speculate_k, block_size=bs,
                    n_table=self.pool.blocks_per_seq, mesh=mesh,
                    rules=rules) if self._spec == "self" else None)
            self._tables = np.zeros((n, self.pool.blocks_per_seq), np.int32)
            self._row_blocks: dict[int, list[int]] = {}
            self._free_rows = list(range(n - 1, -1, -1))
            self._prefilling: dict[int, int] = {}   # row -> next prefill pos
        else:
            self.pool = None
            self.trie = None
            self.cache = KVCacheManager(cfg, n, max_seq=ec.max_seq)
            self.max_seq = self.cache.max_seq
            self._prefill = make_prefill_fn(cfg, mesh=mesh, rules=rules)
            self._step = make_decode_step(cfg, mesh=mesh, rules=rules)

        self._slot_req: dict[int, GenerationRequest] = {}
        self._tokens = np.zeros(n, np.int32)      # current input token
        self._positions = np.zeros(n, np.int32)   # where it will be written
        self._active = np.zeros(n, bool)
        self._waiting: list[GenerationRequest] = []
        self._req_seq = itertools.count()
        self._cond = threading.Condition()
        self._stopped = False
        self._draining = False
        # cross-thread op queue: the pool arrays and the radix trie are
        # loop-thread-only, so the cluster prefix plane's extract/
        # install calls enqueue closures here and the loop runs them
        # between passes (_run_op / _run_ops_locked) — same serialization
        # as every other trie/pool touch, no new locking
        self._ops: list = []
        # prefixes published to the LOCAL trie since the last
        # prefix_export() drain — what the fleet forwards to the
        # cluster directory (bounded; oldest dropped first)
        self._prefix_outbox: list = []

        # metrics (guarded by _cond's lock via _mlock simplicity: own lock)
        self._mlock = threading.Lock()
        self._generated_tokens = 0
        self._requests_completed = 0
        self._decode_iterations = 0
        self._occupancy_sum = 0.0      # Σ active/max_slots per iteration
        self._prefix_hit_tokens = 0
        self._prefix_lookup_tokens = 0
        self._preemptions = 0
        self._peak_active = 0
        self._spec_drafted = 0         # drafted tokens offered to verify
        self._spec_accepted = 0        # drafted tokens accepted
        self._spec_passes = 0          # verify passes run
        # per-ROW step accounting: tokens_per_step = row_tokens /
        # row_steps is exactly 1.0 for plain decode by construction,
        # and 1 + accepted-per-row-pass under speculation — the batch
        # width cancels out, so the gauge isolates speculation's win
        self._row_steps = 0            # (row, compiled-call) pairs
        self._row_tokens = 0           # tokens those pairs emitted

        with _registry_lock:
            self.name = name or f"engine-{next(_engine_seq)}"
            _ENGINES[self.name] = self

        # the thread holds the engine only WEAKLY between passes: an
        # engine abandoned without shutdown() becomes collectable (the
        # loop then exits on its own), instead of a bound-method target
        # pinning the KV pool + a 50 ms-tick thread alive forever
        self._thread = threading.Thread(
            target=_engine_loop, args=(weakref.ref(self),), daemon=True,
            name=f"raytpu-inference-{self.name}")
        self._thread.start()

    # ------------------------------------------------------------ submit

    def submit(self, prompt: Sequence[int], *,
               max_new: Optional[int] = None,
               temperature: float = 0.0,
               seed: int = 0,
               priority: int = PRIORITY_BATCH) -> GenerationRequest:
        """Queue a generation; returns immediately with the request
        mailbox.  Admission happens at the next prefill boundary, in
        (priority, arrival) order — an interactive waiter takes a freed
        slot ahead of batch waiters that arrived earlier.

        Speculation interplay (``EngineConfig.speculate``): greedy
        requests (``temperature == 0``) ride the draft-then-verify path
        and emit the EXACT token stream non-speculative decode would.
        ``temperature > 0`` requests are accepted and transparently
        decode one token per step — never drafted, never a silent
        parity break (the decided alternative to a typed rejection:
        mixed batches are the serving norm, and a sampled request on a
        speculating engine is valid work, not an error).  The typed
        ``SpeculationUnsupported`` is reserved for configurations with
        no speculation path at all (slot engine, bad draft depth) and
        raised at engine construction."""
        ec = self.engine_cfg
        prompt = np.asarray(list(prompt), np.int32)
        max_new = int(max_new if max_new is not None else ec.default_max_new)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token list")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab_size:
            raise ValueError(
                f"prompt tokens out of range [0, {self.cfg.vocab_size})")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        total = int(prompt.size) + max_new
        if total > self.max_seq:
            # this also bounds the paged block budget: BlockPool
            # guarantees n_blocks >= ceil(max_seq / block_size), so any
            # request within the cache width can eventually fit
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new}) = {total} "
                f"exceeds the cache width {self.max_seq}")
        rng = (jax.random.PRNGKey(seed) if temperature > 0.0 else None)
        req = GenerationRequest(next(self._req_seq), prompt, max_new,
                                float(temperature), rng,
                                priority=int(priority))
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("engine is shut down")
            if self._draining:
                raise EngineDrainingError(
                    "engine is draining (planned scale-down)")
            if len(self._waiting) >= ec.max_waiting:
                raise RuntimeError(
                    f"engine admission queue full ({ec.max_waiting})")
            self._waiting.append(req)
            self._cond.notify_all()
        return req

    def generate(self, prompt: Sequence[int], *,
                 max_new: Optional[int] = None, temperature: float = 0.0,
                 seed: int = 0, timeout: Optional[float] = None) -> list[int]:
        """Synchronous convenience wrapper around submit()+result()."""
        return self.submit(prompt, max_new=max_new, temperature=temperature,
                           seed=seed).result(timeout=timeout)

    # ------------------------------------------------------------- loop

    def _loop_pass(self) -> bool:
        """One scheduler pass (reap → admit → decode); False when
        stopped.  Runs on the loop thread, which holds the engine only
        WEAKLY between passes (_engine_loop) so an engine abandoned
        without shutdown() is still collectable."""
        with self._cond:
            # park unless there is work a pass can make progress
            # on: an active row to decode, a prefill to advance, or a
            # waiting request AND a free slot/row to admit it into
            # (waiting alone must not spin when the pool is handed out;
            # paged admission retries at the idle tick because block
            # availability also depends on evictable cached prefixes)
            while (not self._stopped and not self._ops
                   and not self._active.any()
                   and not (self._paged and self._prefilling)
                   and not (self._waiting and self._admission_possible())):
                self._cond.wait(self.engine_cfg.idle_wait_s)
            if self._stopped:
                return False
            if self._ops:
                self._run_ops_locked()
            # reap cancelled waiters even when the pool is full:
            # zombies must not consume max_waiting backpressure
            # (a burst of timed-out clients would otherwise make
            # submit() reject live work as "queue full")
            live = []
            for r in self._waiting:
                if r.cancelled:
                    r._finish()
                else:
                    live.append(r)
            self._waiting = live
            admits = []
            if self._paged:
                self._paged_admit_locked()
            else:
                if self._waiting and self.cache.n_free > 0:
                    # prefill-boundary preemption: freed slots go to the
                    # most urgent class first (stable within a class —
                    # the sort key is (priority, submit id))
                    self._waiting.sort(key=lambda r: (r.priority, r.id))
                while self._waiting and self.cache.n_free > 0:
                    req = self._waiting.pop(0)
                    admits.append((self.cache.alloc(), req))
        for slot, req in admits:
            # per-admit isolation: one bad prefill fails ONE
            # request and returns its slot; neighbors proceed
            try:
                self._admit(slot, req)
            except Exception as e:
                try:
                    self.cache.free(slot)
                except ValueError:            # _admit already returned it
                    pass
                req._finish(e)
        try:
            if self._paged:
                if self._prefilling:
                    # at most ONE chunk per pass: prefill progress is
                    # interleaved with decode so a long prompt cannot
                    # stall its neighbors' token cadence
                    self._prefill_chunk_pass()
                if self._active.any():
                    self._paged_decode_iteration()
            elif self._active.any():
                self._decode_iteration()
        except Exception as e:                # step failure: fail the
            self._fail_all(e)                 # in-flight requests, keep serving
        return True

    def _admission_possible(self) -> bool:
        """Cheap park-predicate check; the real budget decision happens
        in the admission pass."""
        if not self._paged:
            return self.cache.n_free > 0
        return bool(self._free_rows) and (
            self.pool.n_free > 0
            or (self.trie is not None and self.trie.cached_blocks > 0))

    def _drain_pending(self) -> None:
        """Terminal cleanup: fail everything still queued or in-flight."""
        with self._cond:
            self._stopped = True
            pending = list(self._slot_req.values()) + self._waiting
            self._slot_req.clear()
            self._waiting.clear()
            ops, self._ops = self._ops, []
            for _fn, box in ops:
                # a queued prefix op on a dying engine resolves as a
                # dead-replica error — the prefix plane maps it to its
                # local-recompute fallback like every other failure
                box["error"] = EngineStoppedError("engine shut down")
                box["done"] = True
            self._cond.notify_all()
        err = EngineStoppedError("engine shut down")
        for r in pending:
            if not r.done:
                r._finish(err)

    def _admit(self, slot: int, req: GenerationRequest) -> None:
        """Prefill boundary: seed the slot's cache, emit the first token."""
        if req.cancelled:                 # abandoned while queued
            self.cache.free(slot)
            req._finish()
            return
        S = self.cache.max_seq
        n = int(req.prompt.size)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = req.prompt
        logits, k_new, v_new = self._prefill(self.params, padded)
        self.cache.write_prefill(slot, k_new[:, 0], v_new[:, 0])
        tok = int(gpt.sample_token(logits[0, n - 1],
                                   temperature=req.temperature,
                                   rng=req._next_rng()))
        req._emit(tok)
        if self._request_finished(req, tok):
            self.cache.free(slot)
            req._finish()
            self._note_done(req)
            return
        self._slot_req[slot] = req
        self._tokens[slot] = tok
        self._positions[slot] = n
        self._active[slot] = True
        with self._mlock:
            self._peak_active = max(self._peak_active,
                                    self.cache.n_active)

    # ----------------------------------------------------------- paged path

    def _chaos(self, point: str, **ctx) -> Optional[dict]:
        """Fault-plane hook (infer_admit / infer_block_alloc /
        infer_speculate / infer_shard_commit — the last fires after a
        meshed decode iteration installs the sharded pool arrays, the
        spot a multi-host commit could straggle or die):
        zero-overhead gate when no plan is installed.
        Returns the ctx dict when a plan ran — a scripted fn may have
        mutated it (e.g. ``ctx["reject_all"] = True`` forces the
        speculative pass to discard every draft), and the caller reads
        the verdict from it."""
        fi = _fi._active
        if fi is None:
            return None
        ctx["engine"] = self.name
        fi.on_infer(point, ctx)
        return ctx

    def _fr_note(self, req: GenerationRequest) -> None:
        """Flight-recorder copy of a finished request (armed only):
        an ``engine_request`` event the merged ``ray_tpu timeline``
        renders as one engine slice per request, accept/reject counts
        in its args."""
        rec = _fr._active
        if rec is None:
            return
        ev = {
            "t": time.time(), "kind": "engine_request",
            "engine": self.name, "req": req.id,
            "start_t": req.created_wall,
            "tokens": len(req.tokens),
            "spec_accepted": req.spec_accepted,
            "spec_rejected": req.spec_drafted - req.spec_accepted,
        }
        if self._mesh is not None:
            # timeline slices carry the serving geometry so a trace of
            # a sharded fleet says WHICH mesh served each request
            ev["mesh_devices"] = int(np.prod(
                list(self._mesh.devices.shape)))
            ev["tp_shards"] = (self.pool.heads_shards
                               if self.pool is not None else 1)
        rec.note_ingress(ev)

    def _paged_admit_locked(self) -> None:
        """Block-budget admission (called under ``_cond``): admit while
        a decode row is free AND the pool covers the prompt after
        prefix-hit credit.  Head-of-line within (priority, arrival)
        order — a large request that does not fit yet is not overtaken
        (no starvation)."""
        if not (self._waiting and self._free_rows):
            return
        self._waiting.sort(key=lambda r: (r.priority, r.id))
        while self._waiting and self._free_rows:
            req = self._waiting[0]
            try:
                if not self._try_admit_paged(req):
                    break
            except Exception as e:
                self._waiting.pop(0)
                req._finish(e)
                continue
            self._waiting.pop(0)

    def _try_admit_paged(self, req: GenerationRequest) -> bool:
        bs = self.pool.block_size
        prompt = req.prompt
        n_prompt = int(prompt.size)
        p_blocks = -(-n_prompt // bs)
        ids, hit = (self.trie.match(prompt) if self.trie is not None
                    else ([], 0))
        need = p_blocks - len(ids)
        if self.pool.n_free < need and self.trie is not None:
            # pressure: evict unreferenced cached prefixes, LRU-first
            # (the just-matched chain is protected by its new refcount)
            self.trie.evict(need - self.pool.n_free)
        if self.pool.n_free < need:
            for bid in ids:
                self.pool.decref(bid)
            return False
        try:
            self._chaos("infer_admit", req=req.id, need=need,
                        hit_tokens=hit)
        except BaseException:
            for bid in ids:
                self.pool.decref(bid)
            raise
        row = self._free_rows.pop()
        blocks = list(ids)
        for _ in range(need):
            blocks.append(self.pool.alloc())
        self._tables[row, :] = 0
        self._tables[row, :len(blocks)] = blocks
        self._row_blocks[row] = blocks
        self._slot_req[row] = req
        self._prefilling[row] = hit          # prefill resumes past the hit
        occupied = self.engine_cfg.max_slots - len(self._free_rows)
        with self._mlock:
            self._prefix_hit_tokens += hit
            self._prefix_lookup_tokens += n_prompt
            self._peak_active = max(self._peak_active, occupied)
        return True

    def _take_block(self, row: int) -> Optional[int]:
        """A fresh block for ``row``: free list, else LRU prefix
        eviction, else preempt the youngest lowest-priority occupied
        row (``row`` itself last).  Returns None when ``row`` was the
        preemption victim — the caller must stop touching it."""
        while True:
            self._chaos("infer_block_alloc", row=row)
            bid = self.pool.alloc()
            if bid is not None:
                return bid
            if self.trie is not None and self.trie.evict(1):
                continue
            victim = self._pick_victim()
            if victim is None:
                return None
            self._preempt_row(victim)
            if victim == row:
                return None

    def _pick_victim(self) -> Optional[int]:
        """Preemption victim: the youngest request of the least urgent
        class among occupied rows (prefilling or decoding)."""
        occupied = list(self._slot_req)
        if not occupied:
            return None
        return max(occupied,
                   key=lambda r: (self._slot_req[r].priority,
                                  self._slot_req[r].id))

    def _preempt_row(self, row: int) -> None:
        """Block-pressure preemption: donate the row's clean KV chain to
        the prefix index (re-admission will adopt it back if it survives
        eviction), release the blocks, and requeue the request with its
        emitted tokens folded into the prompt — the stream continues
        exactly where it left off."""
        req = self._slot_req[row]
        valid = (int(self._positions[row]) if self._active[row]
                 else self._prefilling.get(row, 0))
        seq = np.concatenate(
            [req.prompt,
             np.asarray(req.tokens[req._consumed:], np.int32)])
        self._insert_prefix(row, seq[:valid])
        self._release_row(row)
        req.prompt = seq
        req._consumed = len(req.tokens)
        with self._mlock:
            self._preemptions += 1
        with self._cond:
            stopped = self._stopped
            if not stopped:
                self._waiting.append(req)
            self._cond.notify_all()
        if stopped:       # raced with shutdown: never leave it hanging
            req._finish(EngineStoppedError("engine shut down"))

    def _insert_prefix(self, row: int, seq: np.ndarray) -> None:
        if self.trie is None or len(seq) == 0:
            return
        self.trie.insert(seq, self._row_blocks[row])

    def _release_row(self, row: int) -> None:
        """Drop the row's references (blocks survive only if the prefix
        index kept them) and return the row to the free list."""
        self._slot_req.pop(row, None)
        self._active[row] = False
        self._prefilling.pop(row, None)
        for bid in self._row_blocks.pop(row, []):
            self.pool.decref(bid)
        self._tables[row, :] = 0
        with self._cond:
            self._free_rows.append(row)
            self._cond.notify_all()

    def _cow_block(self, row: int, bidx: int) -> bool:
        """Copy-on-write: make table entry ``bidx`` exclusively owned
        before a write touches it (the shared case is an adopted
        partially-filled tail).  False = ``row`` was preempted while
        hunting for the copy's block."""
        bid = self._row_blocks[row][bidx]
        if self.pool.refcount(bid) == 1:
            return True
        nb = self._take_block(row)
        if nb is None:
            return False
        self.pool.copy_block(bid, nb)
        self.pool.decref(bid)
        self._row_blocks[row][bidx] = nb
        self._tables[row, bidx] = nb
        return True

    def _prefill_chunk_pass(self) -> None:
        """Advance prefills, occupancy-aware.  At healthy decode
        occupancy (>= half the rows active), ONE chunk per pass — that
        bounds the active streams' per-iteration stall (the point of
        chunking).  Below it, a decode iteration costs nearly the same
        almost-empty as full, so filling rows dominates: run as many
        chunks as there are prefilling rows before the next iteration
        (each picked shortest-remaining-first, so the cheapest prefill
        usually FINISHES within the pass rather than every row
        advancing one step)."""
        n = self.engine_cfg.max_slots
        if 2 * int(self._active.sum()) >= n:
            self._prefill_one_chunk()
            return
        for _ in range(len(self._prefilling)):
            if (not self._prefilling
                    or 2 * int(self._active.sum()) >= n):
                break
            self._prefill_one_chunk()

    def _prefill_one_chunk(self) -> None:
        """Advance ONE prefilling request, shortest-remaining-first
        (ties by arrival).  SRF activates the cheapest prefill soonest
        (occupancy), and — critically for shared prefixes — SERIALIZES
        cold duplicates of the same head: one representative finishes
        and publishes the chain, the rest re-match and jump instead of
        each paying the whole train.  (Round-robin interleaves the
        duplicates so none publishes until nearly everyone has paid.)
        On prompt completion the last real row's logits sample the
        request's first token and the row turns active."""
        row = min(self._prefilling,
                  key=lambda r: (int(self._slot_req[r].prompt.size)
                                 - self._prefilling[r],
                                 self._slot_req[r].id))
        req = self._slot_req[row]
        if req.cancelled:                  # abandoned mid-prefill
            self._release_row(row)
            req._finish()
            self._note_done(req)
            return
        pos = self._prefilling[row]
        bs = self.pool.block_size
        C = self.engine_cfg.prefill_chunk
        prompt = req.prompt
        n = int(prompt.size)
        if self.trie is not None:
            # re-match EVERY advance: a sibling admitted in the same
            # burst publishes the shared head at its own prefill
            # completion, and a colder copy of that head may be
            # mid-chunk-train right here — adopting the published chain
            # jumps its position forward and hands the replaced fresh
            # blocks back (concurrent shared-prefix requests would
            # otherwise each pay the full prefill).  A host-side token
            # walk per chunk is noise next to the chunk itself.
            ids2, hit2 = self.trie.match(prompt)
            if hit2 > pos:
                blocks = self._row_blocks[row]
                for i, nb in enumerate(ids2):
                    self.pool.decref(blocks[i])
                    blocks[i] = nb
                    self._tables[row, i] = nb
                with self._mlock:
                    # the prompt was counted at admission; fold in only
                    # the INCREMENTAL tokens the re-match won
                    self._prefix_hit_tokens += hit2 - pos
                pos = self._prefilling[row] = hit2
            else:
                for bid in ids2:
                    self.pool.decref(bid)
        if (pos == 0 and 2 * n > self.max_seq
                and 2 * int(self._active.sum())
                < self.engine_cfg.max_slots):
            # cold LONG prompt at low decode occupancy: ONE full-width
            # forward (the r10 prefill — gpt.forward with return_kv)
            # seeds every block at once through the table scatter — a
            # long chunk train pays a full-table gather per chunk, and
            # there is little decode cadence to protect.  Under real
            # load (occupancy >= half) long prompts take the chunked
            # path — bounded stall wins; short prompts always chunk
            # (one cheap window beats an S-wide forward).  (pos == 0
            # also means no adopted blocks — the table is exclusive.)
            padded = np.zeros((1, self.max_seq), np.int32)
            padded[0, :n] = prompt
            logits, k_new, v_new = self._prefill(self.params, padded)
            self.pool.write_prefill(self._tables[row], k_new[:, 0],
                                    v_new[:, 0])
            self._finish_prefill(row, req, logits[0, n - 1])
            return
        # the write window [pos, pos+C) must only touch exclusively
        # owned blocks — only the FIRST can be shared (an adopted
        # partial tail), but the scan is cheap
        first = pos // bs
        last = min(-(-(pos + C) // bs), len(self._row_blocks[row]))
        for bidx in range(first, last):
            if not self._cow_block(row, bidx):
                return                     # row preempted under pressure
        n_q = min(C, n - pos)
        chunk_toks = np.zeros(C, np.int32)
        chunk_toks[:n_q] = prompt[pos:pos + n_q]
        logits, k, v = self._chunk(
            self.params, self.pool.k, self.pool.v,
            jnp.asarray(self._tables[row]), jnp.asarray(chunk_toks),
            jnp.int32(pos))
        self.pool.swap(k, v)
        new_pos = pos + n_q
        if new_pos < n:
            self._prefilling[row] = new_pos
            return
        self._finish_prefill(row, req, logits[n_q - 1])

    def _finish_prefill(self, row: int, req: GenerationRequest,
                        last_logits) -> None:
        """Prompt fully in cache: sample the first token from the last
        prompt position's logits; the row turns active (or evicts
        immediately on EOS / max_new == 1)."""
        del self._prefilling[row]
        if self.trie is not None:
            # publish the prompt's full blocks NOW (not at finish):
            # concurrent requests sharing this head re-match at their
            # first chunk and skip the whole head prefill.  Full blocks
            # only — decode writes the partial tail, and sharing it here
            # would force copy-on-write against ourselves.
            full = (int(req.prompt.size) // self.pool.block_size) \
                * self.pool.block_size
            if full > 0:
                self._insert_prefix(row, req.prompt[:full])
                self._note_prefix_published(
                    req.prompt[:full],
                    self._row_blocks[row][:full // self.pool.block_size])
        tok = int(gpt.sample_token(last_logits,
                                   temperature=req.temperature,
                                   rng=req._next_rng()))
        req._emit(tok)
        if self._request_finished(req, tok):
            self._paged_evict(row)
            return
        self._tokens[row] = tok
        self._positions[row] = int(req.prompt.size)
        self._active[row] = True

    def _grow_row(self, row: int) -> bool:
        """Pre-step: make the row's write-target block exist and be
        exclusively owned (decode crossed a block boundary, or the tail
        is still shared).  False = ``row`` was preempted."""
        pos = int(self._positions[row])
        bidx = pos // self.pool.block_size
        blocks = self._row_blocks[row]
        if bidx < len(blocks):
            return self._cow_block(row, bidx)
        nb = self._take_block(row)
        if nb is None:
            return False
        blocks.append(nb)
        self._tables[row, bidx] = nb
        return True

    # ------------------------------------------------- speculative decode

    def _spec_cover(self, row: int, upto: int) -> int:
        """Charge the block budget for speculative positions UP FRONT:
        best-effort growth of the row's chain to cover positions
        through ``upto`` (the write-target block at ``positions[row]``
        already exists and is exclusive — _grow_row ran).  Allocation
        and prefix-LRU eviction only — speculation never PREEMPTS a
        neighbor for tokens that are merely hoped for.  Every granted
        block is appended to ``_row_blocks[row]`` immediately, so a
        later preemption of this row refunds the speculative charge
        with the rest of the chain (_release_row decrefs what the row
        holds, no separate ledger to forget).  Returns the last
        position actually covered; the caller caps the draft length."""
        bs = self.pool.block_size
        pos = int(self._positions[row])
        blocks = self._row_blocks[row]
        for bidx in range(pos // bs + 1, upto // bs + 1):
            if bidx < len(blocks):
                continue     # already covered (defensive: the chain is
            #                  trimmed to the write block after a pass)
            bid = self.pool.alloc()
            if bid is None and self.trie is not None \
                    and self.trie.evict(1):
                bid = self.pool.alloc()
            if bid is None:
                return bidx * bs - 1      # covered through prior block
            blocks.append(bid)
            self._tables[row, bidx] = bid
        return upto

    def _spec_rollback(self, row: int) -> None:
        """Refund the rejected part of the speculative block charge:
        drop chain blocks past the row's next write position (that
        block is KEPT — freeing it would thrash against _grow_row on
        the very next pass).  Rejected lanes' K/V beyond the committed
        length is garbage in owned blocks — masked now, overwritten by
        later decode — so rollback is pure budget accounting."""
        keep = int(self._positions[row]) // self.pool.block_size + 1
        blocks = self._row_blocks[row]
        old = len(blocks)
        if self.pool.release_tail(blocks, keep):
            self._tables[row, len(blocks):old] = 0

    def _spec_propose(self) -> tuple:
        """Per-row draft proposals for this pass.  Returns
        ``(drafts [n, k] int32, want [n] int32)``: row r offers
        ``want[r]`` draft tokens (0 = ride the verify pass as a plain
        one-token lane).  Sampled-temperature rows and rows at their
        max_new boundary never draft; block coverage is charged here
        (_spec_cover) and caps a draft the pool cannot hold."""
        ec = self.engine_cfg
        n, k = ec.max_slots, ec.speculate_k
        drafts = np.zeros((n, k), np.int32)
        want = np.zeros(n, np.int32)
        props = {}
        active_rows = 0
        for row in list(self._slot_req):
            if not self._active[row]:
                continue
            active_rows += 1
            req = self._slot_req[row]
            if req.temperature != 0.0:
                continue      # documented per-row fallback (submit())
            w = min(k, req.max_new - len(req.tokens) - 1)
            if w <= 0:
                continue
            if self._spec == "ngram":
                hist = np.concatenate(
                    [req.prompt,
                     np.asarray(req.tokens[req._consumed:], np.int32)])
                prop = ngram_propose(hist, w)
                if prop.size == 0:
                    continue
                props[row] = prop
                w = min(w, int(prop.size))
            want[row] = w
        # batch-coverage gate: the widened verify prices EVERY active
        # row at W lanes, so a pass where only a few rows draft costs
        # more than the plain step saves on the rest of the batch —
        # speculate only when at least half the batch drafts.  Decided
        # BEFORE blocks are charged or draft steps run, so a skipped
        # pass pays nothing.
        if int((want > 0).sum()) * 2 < active_rows:
            want[:] = 0
            return drafts, want
        for row in np.nonzero(want)[0]:
            pos = int(self._positions[row])
            w = min(int(want[row]),
                    self._spec_cover(row, pos + int(want[row])) - pos)
            if w <= 0:                         # pool cannot hold a draft
                want[row] = 0
                continue
            want[row] = w
            if self._spec == "ngram":
                drafts[row, :w] = props[row][:w]
        if self._spec == "self" and want.any():
            self._spec_self_draft(drafts, want)
        return drafts, want

    def _spec_self_draft(self, drafts: np.ndarray,
                         want: np.ndarray) -> None:
        """Fill ``drafts`` with ONE fused draft-burst call: the whole
        k-step autoregressive truncated-layer loop runs on device
        (argmax feeding the next step), so the host pays a single
        dispatch instead of k round-trips.  Rows draft ``want[row]``
        tokens; dead rows sit out via the burst's lane mask.  The
        drafted K/V for layers < draft_layers lands in the REAL pool —
        identical to what the full model writes there, and the verify
        pass rewrites all drafted positions at all layers anyway."""
        w = np.where(self._active, want, 0).astype(np.int32)
        toks, kp, vp = self._draft(
            self.params, self.pool.k, self.pool.v,
            jnp.asarray(self._tables), jnp.asarray(self._tokens),
            jnp.asarray(self._positions), jnp.asarray(w))
        self.pool.swap(kp, vp)
        toks = np.asarray(toks)
        m = np.arange(toks.shape[1])[None, :] < w[:, None]
        drafts[:, :toks.shape[1]][m] = toks[m]

    def _speculative_iteration(self) -> bool:
        """One draft-then-verify pass over the whole batch; False = no
        drafts this pass (caller falls back to the plain step).  The
        accept rule is greedy and token-exact: lane j's verify logits
        are the model's next-token logits GIVEN the drafted prefix, so
        walking lanes while ``argmax == draft`` — and emitting the
        argmax CORRECTION at the first mismatch — reproduces the
        non-speculative greedy stream exactly (>= 1 token per pass).
        Committed lanes' K/V is already in the pool from the verify
        scatter; the rejected tail's block charge is rolled back."""
        drafts, want = self._spec_propose()
        if not want.any():
            return False
        force_reject = False
        ctx = self._chaos("infer_speculate",
                          rows=int((want > 0).sum()),
                          drafted=int(want.sum()))
        if ctx is not None and ctx.get("reject_all"):
            # forced FULL rejection (chaos): the verify pass still
            # runs and every draft is discarded — exercising the whole
            # charge -> verify -> reject -> rollback path with parity
            # intact (the correction token is the plain step's token)
            force_reject = True
        n = self.engine_cfg.max_slots
        W = self.engine_cfg.speculate_k + 1
        tok_mat = np.zeros((n, W), np.int32)
        tok_mat[:, 0] = self._tokens
        tok_mat[:, 1:] = drafts
        n_tok = np.where(self._active, want + 1, 1).astype(np.int32)
        logits, k, v = self._verify(
            self.params, self.pool.k, self.pool.v,
            jnp.asarray(self._tables), jnp.asarray(tok_mat),
            jnp.asarray(self._positions), jnp.asarray(self._active),
            jnp.asarray(n_tok))
        self.pool.swap(k, v)
        logits = np.asarray(logits)               # [n, W, V]
        with self._mlock:
            self._decode_iterations += 1
            self._spec_passes += 1
            self._occupancy_sum += (float(self._active.sum())
                                    / self.engine_cfg.max_slots)
        greedy = np.asarray(gpt.sample_token(
            logits.reshape(n * W, -1), temperature=0.0)).reshape(n, W)
        stepped = emitted = 0
        for row in list(self._slot_req):
            if not self._active[row]:     # prefilling rows ride along
                continue
            req = self._slot_req[row]
            w = int(want[row])
            if req.temperature != 0.0:
                # sampled lane 0 == the plain step's logits: one token,
                # per-request rng — byte-identical to the fallback path
                tok = int(gpt.sample_token(logits[row, 0],
                                           temperature=req.temperature,
                                           rng=req._next_rng()))
                req._emit(tok)
                stepped += 1
                emitted += 1
                self._positions[row] += 1
                self._tokens[row] = tok
                if self._request_finished(req, tok):
                    self._paged_evict(row)
                continue
            accepted = 0
            finished = False
            for j in range(w + 1):
                tok = int(greedy[row, j])
                req._emit(tok)
                emitted += 1
                self._positions[row] += 1
                self._tokens[row] = tok
                if self._request_finished(req, tok):
                    finished = True       # EOS / max_new mid-burst
                    break
                if j < w and not force_reject \
                        and int(drafts[row, j]) == tok:
                    accepted += 1         # lane j+1's input was right
                    continue
                break                     # first mismatch: corrected
            stepped += 1
            req.spec_drafted += w
            req.spec_accepted += accepted
            with self._mlock:
                self._spec_drafted += w
                self._spec_accepted += accepted
            if finished:
                self._paged_evict(row)    # releases the whole chain
            else:
                self._spec_rollback(row)
        with self._mlock:
            self._row_steps += stepped
            self._row_tokens += emitted
        return True

    def _paged_decode_iteration(self) -> None:
        for row in [r for r in list(self._slot_req) if self._active[r]]:
            req = self._slot_req.get(row)
            if req is None or not self._active[row]:
                continue                  # preempted by an earlier row's
            #                               block hunt this very pass
            if req.cancelled:             # abandoned: free for live work
                self._paged_evict(row, cache_prefix=False)
                continue
            self._grow_row(row)           # False = row preempted; skip
        if not self._active.any():
            return
        # draft-then-verify when configured; False = no row produced a
        # draft this pass (nothing to verify) — the plain one-token
        # step below is the fallback, so an all-sampled or draft-dry
        # batch pays zero speculation overhead.  A speculative pass
        # spans the wall time of ~3 plain steps, and the loop normally
        # advances one prefill chunk per pass — so after a wide pass,
        # run the extra chunks the interleave missed.  Without the
        # compensation, speculation cuts chunk cadence (= TTFT of
        # admitting requests) by the pass width; with it, admission
        # latency stays flat and decode-only passes pay nothing.
        if (self._spec is not None
                and self._speculative_iteration()):
            for _ in range(2):
                if not self._prefilling:
                    break
                self._prefill_one_chunk()
            return
        logits, k, v = self._step(
            self.params, self.pool.k, self.pool.v,
            jnp.asarray(self._tables), jnp.asarray(self._tokens),
            jnp.asarray(self._positions), jnp.asarray(self._active))
        self.pool.swap(k, v)
        if self._mesh is not None:
            # every shard just committed its slice of the donated
            # scatter — the point where a multi-host straggler or
            # mid-commit death would bite, so it is chaos-testable
            self._chaos("infer_shard_commit",
                        tp_shards=self.pool.heads_shards)
        logits = np.asarray(logits)
        with self._mlock:
            self._decode_iterations += 1
            self._occupancy_sum += (float(self._active.sum())
                                    / self.engine_cfg.max_slots)
        greedy = np.asarray(gpt.sample_token(logits, temperature=0.0))
        stepped = 0
        for row in list(self._slot_req):
            if not self._active[row]:     # prefilling rows ride along
                continue
            req = self._slot_req[row]
            if req.temperature == 0.0:
                tok = int(greedy[row])
            else:
                tok = int(gpt.sample_token(logits[row],
                                           temperature=req.temperature,
                                           rng=req._next_rng()))
            req._emit(tok)
            stepped += 1
            self._positions[row] += 1
            self._tokens[row] = tok
            if self._request_finished(req, tok):
                self._paged_evict(row)
        with self._mlock:
            self._row_steps += stepped
            self._row_tokens += stepped

    def _paged_evict(self, row: int, cache_prefix: bool = True) -> None:
        """Natural eviction (EOS / max-tokens / cancel): donate the
        clean KV chain to the prefix index, then release the row."""
        req = self._slot_req[row]
        if cache_prefix and not req.cancelled:
            valid = (int(self._positions[row]) if self._active[row]
                     else self._prefilling.get(row, 0))
            seq = np.concatenate(
                [req.prompt,
                 np.asarray(req.tokens[req._consumed:], np.int32)])
            self._insert_prefix(row, seq[:valid])
        self._release_row(row)
        req._finish()
        self._note_done(req)

    # ------------------------------------------------------------ slot path

    def _decode_iteration(self) -> None:
        logits, k, v = self._step(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(self._tokens), jnp.asarray(self._positions),
            jnp.asarray(self._active))
        self.cache.swap(k, v)
        logits = np.asarray(logits)
        with self._mlock:
            self._decode_iterations += 1
            self._occupancy_sum += (float(self._active.sum())
                                    / self.engine_cfg.max_slots)
        # greedy rows sample in ONE vectorized call (the common/benchmark
        # path: one argmax over [n_slots, vocab], not one dispatch per
        # slot); temperature rows keep their per-request rng
        greedy = np.asarray(gpt.sample_token(logits, temperature=0.0))
        stepped = 0
        for slot in list(self._slot_req):
            req = self._slot_req[slot]
            if req.cancelled:             # abandoned (timeout/disconnect):
                self._evict(slot)         # free the slot for live work
                continue
            if req.temperature == 0.0:
                tok = int(greedy[slot])
            else:
                tok = int(gpt.sample_token(logits[slot],
                                           temperature=req.temperature,
                                           rng=req._next_rng()))
            req._emit(tok)
            stepped += 1
            self._positions[slot] += 1
            self._tokens[slot] = tok
            if self._request_finished(req, tok):
                self._evict(slot)
        with self._mlock:
            self._row_steps += stepped
            self._row_tokens += stepped

    def _request_finished(self, req: GenerationRequest, tok: int) -> bool:
        with self._mlock:
            self._generated_tokens += 1
        eos = self.engine_cfg.eos_token
        return (len(req.tokens) >= req.max_new
                or (eos is not None and tok == eos))

    def _evict(self, slot: int) -> None:
        req = self._slot_req.pop(slot)
        self._active[slot] = False
        self.cache.free(slot)
        req._finish()
        self._note_done(req)
        with self._cond:
            self._cond.notify_all()   # wake loop in case admits are waiting

    def _note_done(self, req: GenerationRequest) -> None:
        with self._mlock:
            self._requests_completed += 1
        self._fr_note(req)

    def _fail_all(self, e: BaseException) -> None:
        if self._paged:
            # a failed chunk/step may have invalidated the DONATED pool
            # buffers; reallocate the pool, drop every reference, and —
            # critically — clear the prefix index: cached prefixes would
            # otherwise point at zeroed blocks and silently corrupt
            # every later prefix hit (the r10 recovery rule generalized
            # to blocks)
            failed = [self._slot_req.pop(row)
                      for row in list(self._slot_req)]
            self._active[:] = False
            self._prefilling.clear()
            self._row_blocks.clear()
            self._tables[:, :] = 0
            if self.trie is not None:
                self.trie.clear()
            self.pool.reset()
            with self._cond:
                self._free_rows = list(
                    range(self.engine_cfg.max_slots - 1, -1, -1))
                self._cond.notify_all()
            # unblock the waiters only AFTER the pool/index are
            # consistent again, so a result() caller reading stats sees
            # the recovered state, not the mid-teardown one
            for req in failed:
                req._finish(e)
            return
        for slot in list(self._slot_req):
            req = self._slot_req.pop(slot)
            self._active[slot] = False
            self.cache.free(slot)
            req._finish(e)
        # the failed step may have invalidated the donated cache buffers
        # (decode_step donates them); reallocate so the engine actually
        # keeps serving instead of poisoning every later request
        self.cache.reset_arrays()

    # ------------------------------------------------------------- admin

    def drain(self) -> None:
        """Begin a graceful drain (planned scale-down): admit nothing
        new — ``submit()`` raises the typed EngineDrainingError so the
        fleet re-routes instead of 500ing — hand already-QUEUED waiters
        back for re-routing the same way, and let the in-flight slots
        decode to completion.  The engine reads drained once
        ``active_slots == 0``; the controller then tears it down.
        Idempotent; a no-op on a stopped engine."""
        with self._cond:
            if self._stopped or self._draining:
                return
            self._draining = True
            waiting, self._waiting = self._waiting, []
            self._cond.notify_all()
        err = EngineDrainingError(
            "engine is draining (planned scale-down)")
        for r in waiting:
            if not r.done:
                r._finish(err)

    # ------------------------------------------- cluster prefix plane

    def _run_ops_locked(self) -> None:
        """Execute queued cross-thread ops on the loop thread (called
        under ``_cond``).  Op errors resolve into the caller's box, the
        loop itself never dies for a bad op.  Op closures must not take
        ``_cond`` (they run holding it) — pool/trie access is safe, the
        row/slot helpers are not."""
        while self._ops:
            fn, box = self._ops.pop(0)
            try:
                box["result"] = fn()
            except BaseException as e:
                box["error"] = e
            box["done"] = True
        self._cond.notify_all()

    def _run_op(self, fn, timeout: float = 10.0):
        """Run ``fn`` on the loop thread and wait for its result — the
        bridge that lets another thread (the fleet's prefix plane)
        touch the loop-thread-only pool/trie.  Raises the op's own
        error, EngineStoppedError on a dead engine, PrefixUnavailable
        on timeout — all of which the caller treats as 'recompute
        locally'."""
        box = {"done": False, "result": None, "error": None}
        deadline = time.monotonic() + timeout
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("engine is shut down")
            self._ops.append((fn, box))
            self._cond.notify_all()
            while not box["done"]:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise PrefixUnavailable(
                        f"engine op timed out after {timeout}s")
                self._cond.wait(left)
        if box["error"] is not None:
            raise box["error"]
        return box["result"]

    def _note_prefix_published(self, tokens: np.ndarray, blocks) -> None:
        """Record a local-trie publication for the cluster directory
        (drained by ``prefix_export``).  Bounded: a fleet that never
        drains costs at most 64 stale records, not unbounded growth."""
        with self._mlock:
            if len(self._prefix_outbox) >= 64:
                self._prefix_outbox.pop(0)
            self._prefix_outbox.append({
                "tokens": [int(t) for t in tokens],
                "blocks": [int(b) for b in blocks],
                "block_size": self.pool.block_size,
                "generation": self.pool.generation,
                # conduit address: lets a FOREIGN fleet process fetch
                # through the node plane's block_fetch handler, which
                # resolves this name in the module engine registry
                "engine": self.name,
            })

    def prefix_export(self) -> list:
        """Drain the prefix publication outbox (cluster-directory feed).
        Empty on non-paged / no-trie engines — the plane then has
        nothing to advertise for this replica."""
        if not self._paged or self.trie is None:
            return []
        with self._mlock:
            out, self._prefix_outbox = self._prefix_outbox, []
        return out

    def prefix_extract(self, tokens, generation: int) -> dict:
        """EXPORT side of replica→replica prefix adoption: gather the
        K/V bytes of a cached block-aligned prefix to host arrays.
        Re-validates everything the directory advertised — the pool
        generation (StalePrefixGeneration when a donated-pool recovery
        reset it: old block ids must never be served) and the live trie
        (PrefixUnavailable when eviction raced the fetch).  Runs on the
        loop thread via the op queue; a dying engine resolves the op as
        EngineStoppedError.  All three are PrefixTransferError /
        ReplicaDeadError shapes the adopter maps to local recompute."""
        if not self._paged or self.trie is None:
            raise PrefixUnavailable("engine has no prefix index")
        toks = np.asarray(list(tokens), np.int32)
        bs = self.pool.block_size
        n = int(toks.size)
        if n < bs or n % bs:
            raise PrefixUnavailable(
                f"prefix length {n} is not block-aligned (bs={bs})")
        want = int(generation)

        def op():
            if want != self.pool.generation:
                raise StalePrefixGeneration(
                    f"pool generation is {self.pool.generation}, entry "
                    f"advertised {want} (pool was reset since publish)")
            # the trie's match caps at len-1 (the last token's logits
            # always rerun); one probe token past the prefix lets the
            # full chain match
            probe = np.concatenate([toks, np.zeros(1, np.int32)])
            ids, hit = self.trie.match(probe)
            try:
                if hit < n:
                    raise PrefixUnavailable(
                        f"only {hit}/{n} prefix tokens still cached "
                        "(evicted since publish)")
                k, v = self.pool.read_blocks(ids[:n // bs])
            finally:
                for bid in ids:
                    self.pool.decref(bid)
            return {"k": k, "v": v, "generation": self.pool.generation,
                    "n_tokens": n, "block_size": bs}
        return self._run_op(op)

    def prefix_install(self, tokens, payload: dict) -> dict:
        """INSTALL side of prefix adoption: write fetched block K/V
        into freshly-allocated local blocks and publish them to the
        local trie — the next admission's match then adopts them under
        the normal refcount/CoW rules, indistinguishable from a locally
        computed prefix.  Never preempts live rows: under block
        pressure it evicts unreferenced cached prefixes only, then
        gives up with PrefixInstallPressure (adoption is an
        optimization; real work is not)."""
        if not self._paged or self.trie is None:
            raise PrefixUnavailable("engine has no prefix index")
        toks = np.asarray(list(tokens), np.int32)
        bs = self.pool.block_size
        n = int(toks.size)
        if n < bs or n % bs:
            raise PrefixUnavailable(
                f"prefix length {n} is not block-aligned (bs={bs})")
        if int(payload.get("block_size", -1)) != bs:
            raise PrefixUnavailable(
                f"holder block_size {payload.get('block_size')} != "
                f"local {bs} (geometry mismatch)")
        n_b = n // bs
        k_new, v_new = payload["k"], payload["v"]
        expect = (self.cfg.n_layers, n_b, self.cfg.n_heads, bs,
                  self.cfg.head_dim)
        if tuple(np.shape(k_new)) != expect \
                or tuple(np.shape(v_new)) != expect:
            raise PrefixUnavailable(
                f"payload shape {np.shape(k_new)} != expected {expect}")

        def op():
            probe = np.concatenate([toks, np.zeros(1, np.int32)])
            ids, hit = self.trie.match(probe)
            for bid in ids:
                self.pool.decref(bid)
            if hit >= n:
                return {"installed": 0, "already": True}
            fresh = []
            for _ in range(n_b):
                bid = self.pool.alloc()
                while bid is None and self.trie.evict(1):
                    bid = self.pool.alloc()
                if bid is None:
                    for b in fresh:
                        self.pool.decref(b)
                    raise PrefixInstallPressure(
                        f"pool cannot hold a {n_b}-block adopted prefix "
                        "without preempting live requests")
                fresh.append(bid)
            self.pool.write_blocks_at(fresh, k_new, v_new)
            self.trie.insert(toks, fresh)
            # the trie holds its own references now (and dedupe dropped
            # any chunk it already had); releasing ours frees exactly
            # the duplicates — the leak audit in tests pins this
            for b in fresh:
                self.pool.decref(b)
            return {"installed": n_b, "already": False}
        return self._run_op(op)

    def stats(self) -> dict:
        with self._cond:
            waiting = len(self._waiting)
            interactive = sum(1 for r in self._waiting
                              if r.priority <= PRIORITY_INTERACTIVE)
            stopped = self._stopped
            draining = self._draining
            occupied = (self.engine_cfg.max_slots - len(self._free_rows)
                        if self._paged else None)
        with self._mlock:
            iters = self._decode_iterations
            occ = (self._occupancy_sum / iters) if iters else 0.0
            generated = self._generated_tokens
            completed = self._requests_completed
            hit_toks = self._prefix_hit_tokens
            lookup_toks = self._prefix_lookup_tokens
            preemptions = self._preemptions
            peak = self._peak_active
            drafted = self._spec_drafted
            accepted = self._spec_accepted
            spec_passes = self._spec_passes
            row_steps = self._row_steps
            row_tokens = self._row_tokens
        out = {
            "max_slots": self.engine_cfg.max_slots,
            "waiting_requests": waiting,
            "waiting_interactive": interactive,
            "stopped": stopped,
            "draining": draining,
            "batch_occupancy": occ,
            "generated_tokens": generated,
            "requests_completed": completed,
            "decode_iterations": iters,
            # tokens emitted per (row, compiled call) pair: exactly 1.0
            # for plain decode by construction, 1 + accepted-per-pass
            # under speculation — batch width cancels out
            "tokens_per_step": (row_tokens / row_steps) if row_steps
                               else 0.0,
            # raw counters behind tokens_per_step so fleet aggregation
            # can reduce exactly instead of averaging averages
            "row_steps": row_steps,
            "row_tokens": row_tokens,
            "paged": self._paged,
            # ---- speculative decoding (zeros when speculate=None /
            # slot engine — the same-run baselines stay comparable)
            "speculate": self._spec,
            "spec_drafted_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_accept_rate": (accepted / drafted) if drafted else 0.0,
            "spec_passes": spec_passes,
            # ---- serving geometry (mesh_devices=1 when unmeshed so
            # fleet aggregation can sum/compare without None checks)
            "mesh_devices": (int(np.prod(list(self._mesh.devices.shape)))
                             if self._mesh is not None else 1),
            "mesh_axes": (dict(zip(self._mesh.axis_names,
                                   self._mesh.devices.shape))
                          if self._mesh is not None else {}),
            "tp_shards": (self.pool.heads_shards
                          if self._paged and self.pool is not None else 1),
        }
        if self._paged:
            pool = self.pool.stats()
            total = pool["blocks_total"]
            out.update({
                # occupied rows (decoding + prefilling): the same
                # concurrency meaning the slot engine reported
                "active_slots": occupied,
                "free_slots": self.engine_cfg.max_slots - occupied,
                "cache_bytes": pool["bytes_total"],
                "cache_bytes_per_device": pool["bytes_per_device"],
                "block_size": pool["block_size"],
                # block COUNTS are replicated across tp shards (heads
                # are what's split): blocks_total is the global
                # admission budget AND the per-device count — both
                # keys reported so neither meaning is silently guessed
                "blocks_total": total,
                "blocks_per_device": pool["blocks_per_device"],
                "blocks_free": pool["blocks_free"],
                "block_utilization": (pool["blocks_used"] / total
                                      if total else 0.0),
                "prefix_cached_blocks": (self.trie.cached_blocks
                                         if self.trie is not None else 0),
                "prefix_hit_tokens": hit_toks,
                "prefix_lookup_tokens": lookup_toks,
                "prefix_hit_rate": (hit_toks / lookup_toks
                                    if lookup_toks else 0.0),
                "preemptions": preemptions,
                "peak_active_requests": peak,
                # fences remotely-advertised block ids across donated-
                # pool recoveries (cluster prefix plane)
                "pool_generation": pool["generation"],
            })
        else:
            cache = self.cache.stats()
            out.update({
                "active_slots": cache["active_slots"],
                "free_slots": cache["free_slots"],
                "cache_bytes": cache["bytes_total"],
                "peak_active_requests": peak,
            })
        return out

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)


def metrics_snapshot() -> list:
    """Per-engine gauges/counters in the metrics exporter's tuple format
    (ray_tpu.metrics.render_prometheus); aggregated by the serve-layer
    /metrics endpoint alongside the per-deployment request counters."""
    with _registry_lock:
        engines = dict(_ENGINES)
    active, waiting, occ, gen, comp = {}, {}, {}, {}, {}
    butil, phit, pcached, preempt = {}, {}, {}, {}
    tps, arate, saccept = {}, {}, {}
    meshdev, tpsh = {}, {}
    for name, eng in sorted(engines.items()):
        st = eng.stats()
        # per-replica/per-model labels (serve fleet sets them) keep a
        # multi-replica fleet from collapsing into one ambiguous series
        key = ((("engine", name),)
               + tuple(sorted(eng.labels.items())))
        active[key] = float(st["active_slots"])
        waiting[key] = float(st["waiting_requests"])
        occ[key] = float(st["batch_occupancy"])
        gen[key] = float(st["generated_tokens"])
        comp[key] = float(st["requests_completed"])
        # paged-cache capacity signal (slot engines report 0): the
        # router/autoscaler read these through fleet_stats, operators
        # through /metrics
        butil[key] = float(st.get("block_utilization", 0.0))
        phit[key] = float(st.get("prefix_hit_rate", 0.0))
        pcached[key] = float(st.get("prefix_cached_blocks", 0))
        preempt[key] = float(st.get("preemptions", 0))
        # speculation signal, per replica: accept-rate is the drafter's
        # quality gauge, tokens/step the latency win it buys
        tps[key] = float(st.get("tokens_per_step", 0.0))
        arate[key] = float(st.get("spec_accept_rate", 0.0))
        saccept[key] = float(st.get("spec_accepted_tokens", 0))
        # serving geometry: 1/1 for unmeshed engines so the series
        # always exists and a sharded rollout shows up as a step change
        meshdev[key] = float(st.get("mesh_devices", 1))
        tpsh[key] = float(st.get("tp_shards", 1))
    zero = {(("engine", "none"),): 0.0}
    return [
        ("ray_tpu_inference_active_slots", "gauge",
         "Cache slots currently decoding, per engine", active or zero),
        ("ray_tpu_inference_waiting_requests", "gauge",
         "Requests queued for a free slot, per engine", waiting or zero),
        ("ray_tpu_inference_batch_occupancy_ratio", "gauge",
         "Mean active/max_slots per decode iteration", occ or zero),
        ("ray_tpu_inference_generated_tokens_total", "counter",
         "Tokens generated since engine start", gen or zero),
        ("ray_tpu_inference_requests_completed_total", "counter",
         "Generation requests completed since engine start", comp or zero),
        ("ray_tpu_inference_block_utilization_ratio", "gauge",
         "Paged KV pool blocks in use / usable blocks", butil or zero),
        ("ray_tpu_inference_prefix_hit_rate", "gauge",
         "Prompt tokens adopted from the radix prefix cache / prompt "
         "tokens seen", phit or zero),
        ("ray_tpu_inference_prefix_cached_blocks", "gauge",
         "Blocks held by the radix prefix index", pcached or zero),
        ("ray_tpu_inference_preemptions_total", "counter",
         "Requests requeued by block-pressure preemption", preempt or zero),
        ("ray_tpu_inference_tokens_per_step", "gauge",
         "Tokens emitted per compiled decode/verify call (speculative "
         "decoding pushes this above 1)", tps or zero),
        ("ray_tpu_inference_spec_accept_rate", "gauge",
         "Drafted tokens accepted by the verify pass / drafted tokens "
         "offered", arate or zero),
        ("ray_tpu_inference_spec_accepted_tokens_total", "counter",
         "Drafted tokens accepted since engine start", saccept or zero),
        ("ray_tpu_inference_mesh_devices", "gauge",
         "Devices in the engine's mesh (1 = unmeshed single device)",
         meshdev or zero),
        ("ray_tpu_inference_tp_shards", "gauge",
         "Tensor-parallel shards of the paged KV pool's heads dim "
         "(block counts are per-device AND global — heads are what's "
         "split)", tpsh or zero),
    ]
