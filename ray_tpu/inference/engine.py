"""Continuous-batching inference engine (Orca-style iteration-level
scheduling over a fixed decode-batch width).

One background loop owns the model state and runs one compiled decode
step per iteration over ALL slots at once.  Between steps — the prefill
boundary — it admits waiting requests into free cache slots (each
admission is one prefill forward that seeds the slot's K/V and produces
the request's first token) and evicts finished ones (EOS / max-tokens),
returning their slots to the pool.  Requests therefore join and leave
MID-DECODE of their neighbors: a long generation never blocks a short
one behind it, and the decode batch stays as full as the offered load
allows — the throughput lever the naive sequential baseline lacks
(benchmarks/serve_bench.py is the A/B receipt).

Tokens stream out per request as they are sampled: GenerationRequest is
a tiny condition-variable mailbox whose ``stream()`` generator the serve
layer turns into chunked transfer-encoding.  All waits are bounded
condition waits (no bare ``Event.wait()`` / ``time.sleep`` polling — the
control-plane lint's blocking rules are the house style even off the
node event loop).

Sampling runs on the host via models.gpt.sample_token — the SAME
function the full-recompute oracle uses, so greedy decode is
token-identical by construction (asserted in tests).  Per-request
temperature/rng stay per-request because sampling is outside the
compiled step; logits [n_slots, vocab] is a small transfer.
"""

from __future__ import annotations

import itertools
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.inference.cache import KVCacheManager
from ray_tpu.inference.decode import make_decode_step, make_prefill_fn
from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules


@dataclass
class EngineConfig:
    """Engine knobs.  max_slots is the decode-batch width AND the cache
    pool size — the engine's entire memory footprint is fixed by it."""
    max_slots: int = 8
    max_seq: Optional[int] = None        # cache width; None = model max_seq
    eos_token: Optional[int] = None      # None = never stop early
    default_max_new: int = 64
    max_waiting: int = 1024              # admission-queue bound (backpressure)
    idle_wait_s: float = 0.05            # loop park interval when empty


# priority classes + the replica-death/draining errors live in the
# jax-free serve.qos module (the fleet's generic machinery imports them
# from there); re-exported here for the engine's own API surface.
from ray_tpu.serve.qos import (PRIORITY_BATCH,           # noqa: F401
                               PRIORITY_INTERACTIVE, EngineDrainingError,
                               ReplicaDeadError, parse_priority)


class EngineStoppedError(ReplicaDeadError):
    """The engine was shut down (replica teardown / chaos kill) with
    this request queued or mid-decode.  A typed subclass so the fleet
    layer can tell a dead replica (retry elsewhere — the generation is
    deterministic from the request) from a request-specific failure
    (do not retry)."""


class GenerationRequest:
    """One in-flight generation: a mailbox the engine appends tokens to
    and consumers drain via ``stream()`` / ``result()``."""

    def __init__(self, req_id: int, prompt: np.ndarray, max_new: int,
                 temperature: float, rng: Optional[jax.Array],
                 priority: int = PRIORITY_BATCH):
        self.id = req_id
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.priority = priority
        self._rng = rng
        self.tokens: list[int] = []
        self.done = False
        self.cancelled = False
        self.error: Optional[BaseException] = None
        self._cond = threading.Condition()
        self.created_s = time.perf_counter()
        self.first_token_s: Optional[float] = None
        self.finished_s: Optional[float] = None

    # ---- engine side -----------------------------------------------------

    def _emit(self, token: int) -> None:
        with self._cond:
            if self.first_token_s is None:
                self.first_token_s = time.perf_counter()
            self.tokens.append(int(token))
            self._cond.notify_all()

    def _finish(self, error: Optional[BaseException] = None) -> None:
        with self._cond:
            self.error = error
            self.done = True
            self.finished_s = time.perf_counter()
            self._cond.notify_all()

    def _next_rng(self) -> Optional[jax.Array]:
        if self._rng is None:
            return None
        self._rng, sub = jax.random.split(self._rng)
        return sub

    # ---- consumer side ---------------------------------------------------

    def cancel(self) -> None:
        """Abandon the request: the engine drops it from the waiting
        queue, or evicts it at the next decode iteration, freeing its
        slot for live work.  Idempotent; a no-op once done."""
        with self._cond:
            self.cancelled = True
            self._cond.notify_all()

    def stream(self, timeout: Optional[float] = None) -> Iterator[int]:
        """Yield generated tokens as they arrive; returns at completion,
        raises the engine-side error if the request failed."""
        i = 0
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        while True:
            with self._cond:
                while len(self.tokens) <= i and not self.done:
                    remain = 0.5
                    if deadline is not None:
                        remain = min(remain, deadline - time.perf_counter())
                        if remain <= 0:
                            raise TimeoutError(
                                f"request {self.id}: no token within "
                                f"{timeout}s")
                    self._cond.wait(timeout=remain)
                if len(self.tokens) > i:
                    tok = self.tokens[i]
                else:                      # done, mailbox drained
                    if self.error is not None:
                        raise self.error
                    return
            yield tok
            i += 1

    def result(self, timeout: Optional[float] = None) -> list[int]:
        """Block until completion; returns the full generated-token list."""
        deadline = (time.perf_counter() + timeout
                    if timeout is not None else None)
        with self._cond:
            while not self.done:
                remain = 0.5
                if deadline is not None:
                    remain = min(remain, deadline - time.perf_counter())
                    if remain <= 0:
                        raise TimeoutError(
                            f"request {self.id} not done within {timeout}s")
                self._cond.wait(timeout=remain)
            if self.error is not None:
                raise self.error
            return list(self.tokens)


# engine registry for /metrics export (weak: an engine dies with its
# replica, the gauge series just disappears — the loop thread also only
# holds its engine weakly, see _engine_loop)
_ENGINES: "weakref.WeakValueDictionary[str, InferenceEngine]" = \
    weakref.WeakValueDictionary()
_engine_seq = itertools.count()
_registry_lock = threading.Lock()


def _engine_loop(ref: "weakref.ref[InferenceEngine]") -> None:
    """Loop-thread driver.  A strong reference exists only DURING a
    pass; between passes the engine is collectable, and a collected
    engine simply ends the thread (its requests are unreachable too,
    short of a consumer-held mailbox, which shutdown()/teardown covers
    for the supported lifecycles)."""
    while True:
        eng = ref()
        if eng is None:
            return
        try:
            alive = eng._loop_pass()
        except BaseException:
            eng._drain_pending()
            raise
        if not alive:
            eng._drain_pending()
            return
        del eng


class InferenceEngine:
    """Continuous-batching engine over one parameter set.

    >>> eng = InferenceEngine(params, cfg, EngineConfig(max_slots=8))
    >>> req = eng.submit([1, 2, 3], max_new=16)
    >>> for tok in req.stream(): ...
    """

    def __init__(self, params, cfg: GPTConfig,
                 engine_cfg: Optional[EngineConfig] = None, *,
                 mesh=None, rules: Rules = DEFAULT_LLM_RULES,
                 name: Optional[str] = None,
                 labels: Optional[dict] = None):
        self.cfg = cfg
        # extra label pairs on this engine's /metrics series (the serve
        # layer sets deployment/replica/model so multi-replica fleets
        # don't collapse into one ambiguous series)
        self.labels = dict(labels) if labels else {}
        self.engine_cfg = engine_cfg or EngineConfig()
        ec = self.engine_cfg
        self.params = params
        self.cache = KVCacheManager(cfg, ec.max_slots, max_seq=ec.max_seq)
        self._prefill = make_prefill_fn(cfg, mesh=mesh, rules=rules)
        self._step = make_decode_step(cfg, mesh=mesh, rules=rules)

        n = ec.max_slots
        self._slot_req: dict[int, GenerationRequest] = {}
        self._tokens = np.zeros(n, np.int32)      # current input token
        self._positions = np.zeros(n, np.int32)   # where it will be written
        self._active = np.zeros(n, bool)
        self._waiting: list[GenerationRequest] = []
        self._req_seq = itertools.count()
        self._cond = threading.Condition()
        self._stopped = False
        self._draining = False

        # metrics (guarded by _cond's lock via _mlock simplicity: own lock)
        self._mlock = threading.Lock()
        self._generated_tokens = 0
        self._requests_completed = 0
        self._decode_iterations = 0
        self._occupancy_sum = 0.0      # Σ active/max_slots per iteration

        with _registry_lock:
            self.name = name or f"engine-{next(_engine_seq)}"
            _ENGINES[self.name] = self

        # the thread holds the engine only WEAKLY between passes: an
        # engine abandoned without shutdown() becomes collectable (the
        # loop then exits on its own), instead of a bound-method target
        # pinning the KV pool + a 50 ms-tick thread alive forever
        self._thread = threading.Thread(
            target=_engine_loop, args=(weakref.ref(self),), daemon=True,
            name=f"raytpu-inference-{self.name}")
        self._thread.start()

    # ------------------------------------------------------------ submit

    def submit(self, prompt: Sequence[int], *,
               max_new: Optional[int] = None,
               temperature: float = 0.0,
               seed: int = 0,
               priority: int = PRIORITY_BATCH) -> GenerationRequest:
        """Queue a generation; returns immediately with the request
        mailbox.  Admission happens at the next prefill boundary, in
        (priority, arrival) order — an interactive waiter takes a freed
        slot ahead of batch waiters that arrived earlier."""
        ec = self.engine_cfg
        prompt = np.asarray(list(prompt), np.int32)
        max_new = int(max_new if max_new is not None else ec.default_max_new)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError("prompt must be a non-empty 1-D token list")
        if prompt.min() < 0 or prompt.max() >= self.cfg.vocab_size:
            raise ValueError(
                f"prompt tokens out of range [0, {self.cfg.vocab_size})")
        if max_new < 1:
            raise ValueError(f"max_new must be >= 1, got {max_new}")
        total = int(prompt.size) + max_new
        if total > self.cache.max_seq:
            raise ValueError(
                f"prompt ({prompt.size}) + max_new ({max_new}) = {total} "
                f"exceeds the cache width {self.cache.max_seq}")
        rng = (jax.random.PRNGKey(seed) if temperature > 0.0 else None)
        req = GenerationRequest(next(self._req_seq), prompt, max_new,
                                float(temperature), rng,
                                priority=int(priority))
        with self._cond:
            if self._stopped:
                raise EngineStoppedError("engine is shut down")
            if self._draining:
                raise EngineDrainingError(
                    "engine is draining (planned scale-down)")
            if len(self._waiting) >= ec.max_waiting:
                raise RuntimeError(
                    f"engine admission queue full ({ec.max_waiting})")
            self._waiting.append(req)
            self._cond.notify_all()
        return req

    def generate(self, prompt: Sequence[int], *,
                 max_new: Optional[int] = None, temperature: float = 0.0,
                 seed: int = 0, timeout: Optional[float] = None) -> list[int]:
        """Synchronous convenience wrapper around submit()+result()."""
        return self.submit(prompt, max_new=max_new, temperature=temperature,
                           seed=seed).result(timeout=timeout)

    # ------------------------------------------------------------- loop

    def _loop_pass(self) -> bool:
        """One scheduler pass (reap → admit → decode); False when
        stopped.  Runs on the loop thread, which holds the engine only
        WEAKLY between passes (_engine_loop) so an engine abandoned
        without shutdown() is still collectable."""
        with self._cond:
            # park unless there is work a pass can make progress
            # on: an active slot to decode, or a waiting request
            # AND a free slot to admit it into (waiting alone
            # must not spin when the pool is fully handed out)
            while (not self._stopped and not self._active.any()
                   and not (self._waiting
                            and self.cache.n_free > 0)):
                self._cond.wait(self.engine_cfg.idle_wait_s)
            if self._stopped:
                return False
            # reap cancelled waiters even when the pool is full:
            # zombies must not consume max_waiting backpressure
            # (a burst of timed-out clients would otherwise make
            # submit() reject live work as "queue full")
            live = []
            for r in self._waiting:
                if r.cancelled:
                    r._finish()
                else:
                    live.append(r)
            self._waiting = live
            admits = []
            if self._waiting and self.cache.n_free > 0:
                # prefill-boundary preemption: freed slots go to the
                # most urgent class first (stable within a class — the
                # sort key is (priority, submit id))
                self._waiting.sort(key=lambda r: (r.priority, r.id))
            while self._waiting and self.cache.n_free > 0:
                req = self._waiting.pop(0)
                admits.append((self.cache.alloc(), req))
        for slot, req in admits:
            # per-admit isolation: one bad prefill fails ONE
            # request and returns its slot; neighbors proceed
            try:
                self._admit(slot, req)
            except Exception as e:
                try:
                    self.cache.free(slot)
                except ValueError:            # _admit already returned it
                    pass
                req._finish(e)
        try:
            if self._active.any():
                self._decode_iteration()
        except Exception as e:                # step failure: fail the
            self._fail_all(e)                 # in-flight requests, keep serving
        return True

    def _drain_pending(self) -> None:
        """Terminal cleanup: fail everything still queued or in-flight."""
        with self._cond:
            self._stopped = True
            pending = list(self._slot_req.values()) + self._waiting
            self._slot_req.clear()
            self._waiting.clear()
        err = EngineStoppedError("engine shut down")
        for r in pending:
            if not r.done:
                r._finish(err)

    def _admit(self, slot: int, req: GenerationRequest) -> None:
        """Prefill boundary: seed the slot's cache, emit the first token."""
        if req.cancelled:                 # abandoned while queued
            self.cache.free(slot)
            req._finish()
            return
        S = self.cache.max_seq
        n = int(req.prompt.size)
        padded = np.zeros((1, S), np.int32)
        padded[0, :n] = req.prompt
        logits, k_new, v_new = self._prefill(self.params, padded)
        self.cache.write_prefill(slot, k_new[:, 0], v_new[:, 0])
        tok = int(gpt.sample_token(logits[0, n - 1],
                                   temperature=req.temperature,
                                   rng=req._next_rng()))
        req._emit(tok)
        if self._request_finished(req, tok):
            self.cache.free(slot)
            req._finish()
            self._note_done()
            return
        self._slot_req[slot] = req
        self._tokens[slot] = tok
        self._positions[slot] = n
        self._active[slot] = True

    def _decode_iteration(self) -> None:
        logits, k, v = self._step(
            self.params, self.cache.k, self.cache.v,
            jnp.asarray(self._tokens), jnp.asarray(self._positions),
            jnp.asarray(self._active))
        self.cache.swap(k, v)
        logits = np.asarray(logits)
        with self._mlock:
            self._decode_iterations += 1
            self._occupancy_sum += (float(self._active.sum())
                                    / self.engine_cfg.max_slots)
        # greedy rows sample in ONE vectorized call (the common/benchmark
        # path: one argmax over [n_slots, vocab], not one dispatch per
        # slot); temperature rows keep their per-request rng
        greedy = np.asarray(gpt.sample_token(logits, temperature=0.0))
        for slot in list(self._slot_req):
            req = self._slot_req[slot]
            if req.cancelled:             # abandoned (timeout/disconnect):
                self._evict(slot)         # free the slot for live work
                continue
            if req.temperature == 0.0:
                tok = int(greedy[slot])
            else:
                tok = int(gpt.sample_token(logits[slot],
                                           temperature=req.temperature,
                                           rng=req._next_rng()))
            req._emit(tok)
            self._positions[slot] += 1
            self._tokens[slot] = tok
            if self._request_finished(req, tok):
                self._evict(slot)

    def _request_finished(self, req: GenerationRequest, tok: int) -> bool:
        with self._mlock:
            self._generated_tokens += 1
        eos = self.engine_cfg.eos_token
        return (len(req.tokens) >= req.max_new
                or (eos is not None and tok == eos))

    def _evict(self, slot: int) -> None:
        req = self._slot_req.pop(slot)
        self._active[slot] = False
        self.cache.free(slot)
        req._finish()
        self._note_done()
        with self._cond:
            self._cond.notify_all()   # wake loop in case admits are waiting

    def _note_done(self) -> None:
        with self._mlock:
            self._requests_completed += 1

    def _fail_all(self, e: BaseException) -> None:
        for slot in list(self._slot_req):
            req = self._slot_req.pop(slot)
            self._active[slot] = False
            self.cache.free(slot)
            req._finish(e)
        # the failed step may have invalidated the donated cache buffers
        # (decode_step donates them); reallocate so the engine actually
        # keeps serving instead of poisoning every later request
        self.cache.reset_arrays()

    # ------------------------------------------------------------- admin

    def drain(self) -> None:
        """Begin a graceful drain (planned scale-down): admit nothing
        new — ``submit()`` raises the typed EngineDrainingError so the
        fleet re-routes instead of 500ing — hand already-QUEUED waiters
        back for re-routing the same way, and let the in-flight slots
        decode to completion.  The engine reads drained once
        ``active_slots == 0``; the controller then tears it down.
        Idempotent; a no-op on a stopped engine."""
        with self._cond:
            if self._stopped or self._draining:
                return
            self._draining = True
            waiting, self._waiting = self._waiting, []
            self._cond.notify_all()
        err = EngineDrainingError(
            "engine is draining (planned scale-down)")
        for r in waiting:
            if not r.done:
                r._finish(err)

    def stats(self) -> dict:
        with self._cond:
            waiting = len(self._waiting)
            interactive = sum(1 for r in self._waiting
                              if r.priority <= PRIORITY_INTERACTIVE)
            stopped = self._stopped
            draining = self._draining
        with self._mlock:
            iters = self._decode_iterations
            occ = (self._occupancy_sum / iters) if iters else 0.0
            generated = self._generated_tokens
            completed = self._requests_completed
        cache = self.cache.stats()
        return {
            "active_slots": cache["active_slots"],
            "free_slots": cache["free_slots"],
            "max_slots": self.engine_cfg.max_slots,
            "waiting_requests": waiting,
            "waiting_interactive": interactive,
            "stopped": stopped,
            "draining": draining,
            "batch_occupancy": occ,
            "generated_tokens": generated,
            "requests_completed": completed,
            "decode_iterations": iters,
            "cache_bytes": cache["bytes_total"],
        }

    def shutdown(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)


def metrics_snapshot() -> list:
    """Per-engine gauges/counters in the metrics exporter's tuple format
    (ray_tpu.metrics.render_prometheus); aggregated by the serve-layer
    /metrics endpoint alongside the per-deployment request counters."""
    with _registry_lock:
        engines = dict(_ENGINES)
    active, waiting, occ, gen, comp = {}, {}, {}, {}, {}
    for name, eng in sorted(engines.items()):
        st = eng.stats()
        # per-replica/per-model labels (serve fleet sets them) keep a
        # multi-replica fleet from collapsing into one ambiguous series
        key = ((("engine", name),)
               + tuple(sorted(eng.labels.items())))
        active[key] = float(st["active_slots"])
        waiting[key] = float(st["waiting_requests"])
        occ[key] = float(st["batch_occupancy"])
        gen[key] = float(st["generated_tokens"])
        comp[key] = float(st["requests_completed"])
    zero = {(("engine", "none"),): 0.0}
    return [
        ("ray_tpu_inference_active_slots", "gauge",
         "Cache slots currently decoding, per engine", active or zero),
        ("ray_tpu_inference_waiting_requests", "gauge",
         "Requests queued for a free slot, per engine", waiting or zero),
        ("ray_tpu_inference_batch_occupancy_ratio", "gauge",
         "Mean active/max_slots per decode iteration", occ or zero),
        ("ray_tpu_inference_generated_tokens_total", "counter",
         "Tokens generated since engine start", gen or zero),
        ("ray_tpu_inference_requests_completed_total", "counter",
         "Generation requests completed since engine start", comp or zero),
    ]
