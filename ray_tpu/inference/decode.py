"""Incremental (KV-cache) decode for the GPT: prefill + one-token step.

Two compiled programs, both with STATIC shapes so each compiles exactly
once regardless of request mix — and (no-mesh path) once per (config,
rules) across ALL engines, so a fleet scaling out replicas or
multiplexing model variants reuses the compiled pair instead of paying
a per-engine recompile:

  * prefill — the ordinary training forward with ``return_kv=True``
    (models/gpt.py) over the prompt padded to the cache width.  Same
    math, same code path: the K/V that seed the cache cannot drift from
    the oracle.  Causality makes right-padding free — positions beyond
    the prompt produce garbage K/V that the per-slot kv_lengths mask
    hides and later decode steps overwrite.
  * decode_step — one token for EVERY slot at once ([n_slots] batch).
    Each slot sits at its own sequence position, so the cache write is a
    one-hot scatter on the position axis and attention masks each row to
    its own valid prefix (ops/attention.py kv_lengths).  Inactive slots
    ride along masked — the batch width never changes, which is what
    lets the engine admit/evict between steps without recompilation
    (Orca's iteration-level scheduling in pjit form).

The step mirrors gpt._transformer_layer's einsums exactly (dense MLP
path); greedy token-parity with full-recompute ``generate()`` is pinned
by tests/test_inference.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules

# engines with the same (cfg, rules) on the default (no-mesh) path share
# ONE jitted prefill/step pair: the compiled programs are stateless
# (params/cache are arguments; donation is per-call), and a fleet of N
# replicas x M model variants would otherwise pay N*M identical
# compilations — a multi-second head-of-line stall every time the
# autoscaler grows or the multiplexer loads a variant.  Meshed engines
# skip the cache (mesh identity isn't a safe dict key across tests).
_FN_CACHE: dict = {}


def _cached(kind: str, cfg: GPTConfig, mesh, rules, build):
    if mesh is not None:
        return build()
    key = (kind, cfg, rules if isinstance(rules, tuple) else id(rules))
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = _FN_CACHE[key] = build()
    return fn


def make_prefill_fn(cfg: GPTConfig, *, mesh=None,
                    rules: Rules = DEFAULT_LLM_RULES):
    """jitted (params, tokens [b, S]) -> (logits [b, S, V], k, v
    [L, b, h, S, hd] each)."""
    if cfg.n_experts:
        raise NotImplementedError(
            "the inference engine has no MoE decode path yet "
            "(expert dispatch per cached token)")

    def build():
        @jax.jit
        def prefill(params, tokens):
            logits, (k, v) = gpt.forward(params, tokens, cfg, mesh=mesh,
                                         rules=rules, return_kv=True)
            return logits, k, v
        return prefill

    return _cached("prefill", cfg, mesh, rules, build)


def make_decode_step(cfg: GPTConfig, *, mesh=None,
                     rules: Rules = DEFAULT_LLM_RULES):
    """jitted one-token step over the whole slot batch.

    (params, k_cache, v_cache [L, b, h, S, hd], tokens [b] int32,
     positions [b] int32, active [b] bool)
        -> (logits [b, vocab] f32, k_cache, v_cache)

    ``tokens`` are the slots' current input tokens, each sitting at
    ``positions[slot]``; the step writes that token's K/V into the cache
    (masked by ``active`` so parked slots stay untouched), attends over
    positions [0, positions[slot]] and returns next-token logits.
    """
    if cfg.n_experts:
        raise NotImplementedError(
            "the inference engine has no MoE decode path yet "
            "(expert dispatch per cached token)")
    h, hd = cfg.n_heads, cfg.head_dim

    def build():
        return _make_step(cfg, mesh, rules, h, hd)

    return _cached("step", cfg, mesh, rules, build)


def _make_step(cfg, mesh, rules, h, hd):
    @partial(jax.jit, donate_argnums=(1, 2))
    def step(params, k_cache, v_cache, tokens, positions, active):
        b = tokens.shape[0]
        S = k_cache.shape[3]
        x = (params["wte"][tokens] + params["wpe"][positions])
        x = x[:, None, :].astype(cfg.dtype)               # [b, 1, d]
        # one-hot write mask on the position axis, zeroed for parked slots
        write = ((jnp.arange(S)[None, :] == positions[:, None])
                 & active[:, None])                       # [b, S]
        kv_len = jnp.where(active, positions + 1, 1)      # >=1: no NaN rows

        def layer(x, xs):
            lp, ck, cv = xs                               # ck/cv [b,h,S,hd]
            y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
            qkv = jnp.einsum("bsd,de->bse", y,
                             lp["wqkv"].astype(cfg.dtype))
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):                                 # [b,1,d]->[b,h,1,hd]
                return t.reshape(b, 1, h, hd).transpose(0, 2, 1, 3)

            kh, vh = heads(k), heads(v)                   # [b, h, 1, hd]
            ck = jnp.where(write[:, None, :, None], kh, ck)
            cv = jnp.where(write[:, None, :, None], vh, cv)
            o = attention(heads(q), ck, cv, causal=False,
                          kv_lengths=kv_len, impl="reference")
            o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.d_model)
            o = jnp.einsum("bsd,de->bse", o, lp["wo"].astype(cfg.dtype)) \
                + lp["bo"].astype(cfg.dtype)
            x = x + o
            y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
            u = jnp.einsum("bsd,df->bsf", y,
                           lp["w_up"].astype(cfg.dtype)) \
                + lp["b_up"].astype(cfg.dtype)
            u = jax.nn.gelu(u)
            dn = jnp.einsum("bsf,fd->bsd", u,
                            lp["w_down"].astype(cfg.dtype)) \
                + lp["b_down"].astype(cfg.dtype)
            return x + dn, (ck, cv)

        x, (k_cache, v_cache) = lax.scan(
            layer, x, (params["layers"], k_cache, v_cache))
        logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
        return logits, k_cache, v_cache

    return step


def clear_fn_cache() -> None:
    """Drop the shared compiled-function cache (tests / benchmarks that
    want cold-compile timings)."""
    _FN_CACHE.clear()
