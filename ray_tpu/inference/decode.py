"""Incremental (KV-cache) decode for the GPT: paged decode + chunked
prefill + speculative verify (production), slot decode + full prefill
(legacy baseline).

All programs have STATIC shapes so each compiles exactly once
regardless of request mix — and (no-mesh path) once per (config,
rules, geometry) across ALL engines, so a fleet scaling out replicas
or multiplexing model variants reuses the compiled set instead of
paying a per-engine recompile.

Paged path (cache.BlockPool):

  * chunk_prefill — a fixed-width window of the prompt ([C] tokens at
    positions start..start+C) runs one forward layer-by-layer against
    the BLOCK POOL: each layer writes the window's K/V through the
    block table, then attends over the gathered table (earlier chunks'
    K/V included), each query row masked to its OWN causal horizon.
    Long prompts therefore prefill as a sequence of bounded-cost steps
    the engine interleaves with decode iterations — a long prompt
    stops stalling neighbors' token cadence.
  * paged_decode_step — one token for EVERY row at once; the cache
    write is a per-row (block, offset) scatter into the pool (inactive
    rows redirected to the scratch block), attention gathers each
    row's block table and masks to its valid prefix
    (ops/attention.paged_attention).
  * spec_verify_step — the decode step widened to a [b, W] token
    window (W = speculate_k + 1): column 0 is each row's current input
    token, columns 1.. are DRAFTED continuations.  One call scores all
    W positions per row (each query masked to its own causal horizon,
    exactly the chunk-prefill formulation batched over rows) and lands
    every position's K/V in ONE donated scatter — draft-then-verify
    speculation's verify pass (Leviathan et al. 2023).  Lanes past a
    row's real draft count are redirected to the scratch block / dummy
    context column so a short draft can ride a fixed-width program.
  * paged_draft_step — the truncated-layer self-draft BURST: k
    autoregressive draft tokens in one compiled call (a lax.scan over
    draft positions, each scanning only the FIRST ``draft_layers``
    layers straight into the head, argmax feeding the next step — zero
    extra weights).  K/V for layers < draft_layers are
    bit-identical to what the full model writes at those layers (layer
    l only depends on layers < l), so drafting through the real pool
    corrupts nothing, and the verify pass overwrites every drafted
    position at all layers anyway.

The host-side n-gram drafter (``ngram_propose`` — prompt-lookup
decoding, Saxena 2023) lives here too: it proposes the continuation
that followed the most recent earlier occurrence of the sequence's
trailing n-gram.  Zero weights, zero device work — repetitive
generations (and shared-prefix serving mixes) accept most of it.

Legacy slot path (cache.KVCacheManager, engine ``paged=False``):

  * prefill — the ordinary training forward with ``return_kv=True``
    (models/gpt.py) over the prompt padded to the cache width.
  * decode_step — one-hot scatter on the position axis of the
    ``[L, n_slots, h, S, hd]`` cache, per-row kv_lengths masking.

All step bodies mirror gpt._transformer_layer's einsums exactly; MoE
configs dispatch through gpt._moe_mlp per token window (paged path
only — the slot path stays the frozen dense baseline).  With a mesh the
paged bodies are sharding-annotated for Megatron-style tensor
parallelism: pools heads-sharded per POOL_AXES, per-device attention
over local heads, one collective at the output projection, the donated
one-scatter commit preserved per shard.  Greedy token-parity with
full-recompute ``generate()`` is pinned by tests/test_inference.py +
tests/test_paged_cache.py (mesh=None) and tests/test_sharded_decode.py
(multi-device CPU meshes).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules


class MoEDecodeUnsupported(NotImplementedError):
    """The legacy SLOT decode path has no MoE support (it is the frozen
    dense A/B baseline; the paged engine serves MoE via gpt._moe_mlp).
    Typed so the gap fails EARLY and clearly — at step construction
    time, never mid-decode with slots already held — and so callers can
    distinguish the known capability gap from a generic failure."""

    def __init__(self, cfg: GPTConfig):
        super().__init__(
            f"the legacy slot decode path has no MoE support "
            f"(n_experts={cfg.n_experts}); serve this config with the "
            f"paged engine (EngineConfig.paged=True — it dispatches "
            f"experts per token window via gpt._moe_mlp), or with a "
            f"dense MLP (n_experts=0), or the training forward")

class SpeculationUnsupported(ValueError):
    """Speculative decoding was requested for a configuration that has
    no speculation path.  Typed and raised at engine CONSTRUCTION time
    (like MoEDecodeUnsupported) so the gap fails early and callers can
    tell the known capability boundary from a generic failure.  The
    supported surface: the PAGED engine only (the slot engine is the
    frozen A/B baseline), and the self-drafter needs
    ``1 <= draft_layers < n_layers`` (a full-depth draft is just the
    model twice).  ``temperature > 0`` requests are NOT an error — they
    transparently fall back to non-speculative decode per row (see
    InferenceEngine.submit)."""


# engines with the same (cfg, rules, mesh) share ONE jitted
# prefill/step pair: the compiled programs are stateless (params/cache
# are arguments; donation is per-call), and a fleet of N replicas x M
# model variants would otherwise pay N*M identical compilations — a
# multi-second head-of-line stall every time the autoscaler grows or
# the multiplexer loads a variant.  Meshed engines key on the mesh's
# IDENTITY plus its axis shape: a Mesh is not hashable-by-value across
# tests, but the same mesh object reused by every replica of a sharded
# fleet must hit the cache (the exact regression the no-mesh path fixed
# once already).  The shape tuple bounds the blast radius of id() reuse
# after GC: a recycled id only collides with a mesh of identical axes.
_FN_CACHE: dict = {}


def _cached(kind: str, cfg: GPTConfig, mesh, rules, build):
    mesh_key = (None if mesh is None
                else (id(mesh), tuple(mesh.shape.items())))
    key = (kind, cfg, mesh_key,
           rules if isinstance(rules, tuple) else id(rules))
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = _FN_CACHE[key] = build()
    return fn


# logical axes of the paged pool arrays [L, N+1, heads, bs, hd]: the
# HEADS dim is the sharded one (Megatron-style tensor parallelism —
# every device holds ALL blocks with h/tp of each block's heads, so the
# host-side table/refcount/CoW logic is shard-oblivious).  The layers
# dim is deliberately NOT "layers": the pool must never shard over pp
# (the scan body dynamic-slices it per layer).
POOL_AXES = (None, None, "heads", None, "kv")


def _mlp_block(y, lp, cfg, mesh, rules):
    """The step bodies' MLP: the dense einsums mirroring
    gpt._transformer_layer, or — when the config is MoE — the training
    forward's expert dispatch (gpt._moe_mlp) applied to the step's
    token window, the load-balance aux loss discarded (inference).
    Per-token routing is position-independent, so incremental windows
    route exactly like the full forward; expert CAPACITY is per window
    (C = ceil(cf·k·s_window/E)), so token-exact parity with the
    full-sequence oracle holds whenever capacity never binds
    (capacity_factor >= n_experts / expert_top_k guarantees it; a
    single-token decode window can never drop regardless).
    y [b, s, d] -> [b, s, d]."""
    if cfg.n_experts:
        dn, _ = gpt._moe_mlp(y, lp, cfg, mesh, rules)
        return dn
    u = jnp.einsum("bsd,df->bsf", y, lp["w_up"].astype(cfg.dtype)) \
        + lp["b_up"].astype(cfg.dtype)
    u = gpt._constrain(u, ("batch", "seq", "mlp"), mesh, rules)
    u = jax.nn.gelu(u)
    return jnp.einsum("bsf,fd->bsd", u, lp["w_down"].astype(cfg.dtype)) \
        + lp["b_down"].astype(cfg.dtype)


def make_prefill_fn(cfg: GPTConfig, *, mesh=None,
                    rules: Rules = DEFAULT_LLM_RULES):
    """jitted (params, tokens [b, S]) -> (logits [b, S, V], k, v
    [L, b, h, S, hd] each).  MoE configs ride gpt.forward's own expert
    dispatch; with a mesh the K/V come back heads-sharded, matching the
    pool layout (POOL_AXES)."""

    def build():
        @jax.jit
        def prefill(params, tokens):
            logits, (k, v) = gpt.forward(params, tokens, cfg, mesh=mesh,
                                         rules=rules, return_kv=True)
            return logits, k, v
        return prefill

    return _cached("prefill", cfg, mesh, rules, build)


def make_decode_step(cfg: GPTConfig, *, mesh=None,
                     rules: Rules = DEFAULT_LLM_RULES):
    """jitted one-token step over the whole slot batch.

    (params, k_cache, v_cache [L, b, h, S, hd], tokens [b] int32,
     positions [b] int32, active [b] bool)
        -> (logits [b, vocab] f32, k_cache, v_cache)

    ``tokens`` are the slots' current input tokens, each sitting at
    ``positions[slot]``; the step writes that token's K/V into the cache
    (masked by ``active`` so parked slots stay untouched), attends over
    positions [0, positions[slot]] and returns next-token logits.
    """
    if cfg.n_experts:
        raise MoEDecodeUnsupported(cfg)
    h, hd = cfg.n_heads, cfg.head_dim

    def build():
        return _make_step(cfg, mesh, rules, h, hd)

    return _cached("step", cfg, mesh, rules, build)


def _make_step(cfg, mesh, rules, h, hd):
    @partial(jax.jit, donate_argnums=(1, 2))
    def step(params, k_cache, v_cache, tokens, positions, active):
        b = tokens.shape[0]
        S = k_cache.shape[3]
        x = (params["wte"][tokens] + params["wpe"][positions])
        x = x[:, None, :].astype(cfg.dtype)               # [b, 1, d]
        # one-hot write mask on the position axis, zeroed for parked slots
        write = ((jnp.arange(S)[None, :] == positions[:, None])
                 & active[:, None])                       # [b, S]
        kv_len = jnp.where(active, positions + 1, 1)      # >=1: no NaN rows

        def layer(x, xs):
            lp, ck, cv = xs                               # ck/cv [b,h,S,hd]
            y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
            qkv = jnp.einsum("bsd,de->bse", y,
                             lp["wqkv"].astype(cfg.dtype))
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):                                 # [b,1,d]->[b,h,1,hd]
                return t.reshape(b, 1, h, hd).transpose(0, 2, 1, 3)

            kh, vh = heads(k), heads(v)                   # [b, h, 1, hd]
            ck = jnp.where(write[:, None, :, None], kh, ck)
            cv = jnp.where(write[:, None, :, None], vh, cv)
            o = attention(heads(q), ck, cv, causal=False,
                          kv_lengths=kv_len, impl="reference")
            o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.d_model)
            o = jnp.einsum("bsd,de->bse", o, lp["wo"].astype(cfg.dtype)) \
                + lp["bo"].astype(cfg.dtype)
            x = x + o
            y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
            u = jnp.einsum("bsd,df->bsf", y,
                           lp["w_up"].astype(cfg.dtype)) \
                + lp["b_up"].astype(cfg.dtype)
            u = jax.nn.gelu(u)
            dn = jnp.einsum("bsf,fd->bsd", u,
                            lp["w_down"].astype(cfg.dtype)) \
                + lp["b_down"].astype(cfg.dtype)
            return x + dn, (ck, cv)

        x, (k_cache, v_cache) = lax.scan(
            layer, x, (params["layers"], k_cache, v_cache))
        logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
        return logits, k_cache, v_cache

    return step


# ---------------------------------------------------------------------------
# paged path


def make_paged_decode_step(cfg: GPTConfig, *, block_size: int,
                           n_table: int, mesh=None,
                           rules: Rules = DEFAULT_LLM_RULES):
    """jitted one-token step over the whole row batch, block-pool cache.

    (params, k_pool, v_pool [L, N, h, bs, hd], tables [b, T] int32,
     tokens [b] int32, positions [b] int32, active [b] bool)
        -> (logits [b, vocab] f32, k_pool, v_pool)

    Each row's current token K/V scatters into the pool at
    ``(tables[row, pos // bs], pos % bs)`` — inactive rows are
    redirected to the scratch block (id 0) so the scatter needs no
    conditional — and attention gathers the row's table, masked to its
    valid prefix (ops/attention.paged_attention).  Tail blocks are
    per-row exclusive (the engine copy-on-writes shared tails before
    the step), so active rows never collide in the scatter.

    With a mesh, the pools are heads-sharded (POOL_AXES) and the body
    carries sharding constraints mirroring gpt._transformer_layer:
    qkv projection, gathered context, and attention run per-device
    over local heads with ONE collective at the output/head projection
    (Megatron TP); the donated one-scatter commit stays per-shard
    (the scatter's advanced axes — block, offset — are unsharded).
    MoE configs dispatch through gpt._moe_mlp per decode window.
    """
    h, hd, bs = cfg.n_heads, cfg.head_dim, int(block_size)

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def step(params, k_pool, v_pool, tables, tokens, positions,
                 active):
            b = tokens.shape[0]
            L = k_pool.shape[0]
            T = tables.shape[1]
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            x = (params["wte"][tokens] + params["wpe"][positions])
            x = x[:, None, :].astype(cfg.dtype)               # [b, 1, d]
            rows = jnp.arange(b)
            bidx = jnp.where(active, tables[rows, positions // bs], 0)
            off = jnp.where(active, positions % bs, 0)
            kv_len = jnp.where(active, positions + 1, 1)      # >=1: no NaN

            # the pools are CLOSED OVER by the scan body and read with a
            # per-layer dynamic slice + table gather; the new K/V come
            # back as stacked scan outputs and land in ONE donated
            # scatter after the scan.  (Carrying the pools through the
            # scan as xs/ys — the obvious formulation — copies the
            # ENTIRE pool every call, a fixed ~2x-pool-bytes tax per
            # decode step that dwarfs the actual compute.)
            def layer(x, xs):
                lp, li = xs
                ck, cv = k_pool[li], v_pool[li]    # [N, h, bs, hd]
                y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
                qkv = jnp.einsum("bsd,de->bse", y,
                                 lp["wqkv"].astype(cfg.dtype))
                qkv = gpt._constrain(qkv, ("batch", "seq", "qkv"),
                                     mesh, rules)
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(t):                      # [b,1,d]->[b,h,1,hd]
                    return t.reshape(b, 1, h, hd).transpose(0, 2, 1, 3)

                def gather(pool):                  # -> [b, h, S, hd]
                    g = pool[tables]               # [b, T, h, bs, hd]
                    return g.transpose(0, 2, 1, 3, 4).reshape(
                        b, h, T * bs, hd)

                kh = k.reshape(b, h, hd)
                vh = v.reshape(b, h, hd)
                # insert the current token's K/V at its own position in
                # the gathered context — key ORDER stays position-major,
                # so the masked softmax is numerically identical to the
                # write-then-gather formulation (and to the slot step)
                ctx_k = gather(ck).at[rows, :, positions, :].set(
                    kh.astype(ck.dtype))
                ctx_v = gather(cv).at[rows, :, positions, :].set(
                    vh.astype(cv.dtype))
                ctx_k = gpt._constrain(
                    ctx_k, ("batch", "heads", None, "kv"), mesh, rules)
                ctx_v = gpt._constrain(
                    ctx_v, ("batch", "heads", None, "kv"), mesh, rules)
                o = attention(heads(q), ctx_k, ctx_v, causal=False,
                              kv_lengths=kv_len, impl="reference")
                o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.d_model)
                o = jnp.einsum("bsd,de->bse", o,
                               lp["wo"].astype(cfg.dtype)) \
                    + lp["bo"].astype(cfg.dtype)
                x = x + o
                x = gpt._constrain(x, ("batch", "seq", "embed"),
                                   mesh, rules)
                y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
                dn = _mlp_block(y, lp, cfg, mesh, rules)
                return x + dn, (kh, vh)

            x, (ks, vs) = lax.scan(
                layer, x, (params["layers"], jnp.arange(L)))
            # ks/vs [L, b, h, hd] -> one in-place scatter on the donated
            # pools at each row's (block, offset); inactive rows hit the
            # scratch block
            k_pool = k_pool.at[:, bidx, :, off, :].set(
                ks.transpose(1, 0, 2, 3).astype(k_pool.dtype))
            v_pool = v_pool.at[:, bidx, :, off, :].set(
                vs.transpose(1, 0, 2, 3).astype(v_pool.dtype))
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
            return logits, k_pool, v_pool

        return step

    return _cached(("paged_step", bs, int(n_table)), cfg, mesh, rules,
                   build)


def make_chunk_prefill_fn(cfg: GPTConfig, *, chunk: int, block_size: int,
                          n_table: int, mesh=None,
                          rules: Rules = DEFAULT_LLM_RULES):
    """jitted fixed-width prefill chunk against the block pool.

    (params, k_pool, v_pool [L, N, h, bs, hd], table [T] int32,
     tokens [C] int32, start int32)
        -> (logits [C, vocab] f32, k_pool, v_pool)

    Processes prompt positions ``start .. start+C``: each layer writes
    the window's K/V through the block table (rows past the table's
    span are redirected to the scratch block), then attends over the
    gathered table with each query row masked to its OWN causal horizon
    (key position <= query position) — so earlier chunks' cached K/V,
    including an adopted prefix from the radix index, participates
    exactly as in a full forward.  Pad rows past the prompt compute
    garbage that lands in masked positions and is overwritten by
    decode; the caller reads only the rows it needs.  The engine
    interleaves one chunk per scheduler pass with decode iterations
    (chunked prefill: bounded prefill cost per token cadence).

    Sharding and MoE follow the decode step: heads-sharded pools +
    per-device attention with one collective at the output projection,
    and gpt._moe_mlp expert dispatch over the chunk window.
    """
    h, hd = cfg.n_heads, cfg.head_dim
    bs, C, T = int(block_size), int(chunk), int(n_table)
    S = T * bs

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def chunk_fn(params, k_pool, v_pool, table, tokens, start):
            L = k_pool.shape[0]
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            pos = start + jnp.arange(C, dtype=jnp.int32)       # [C]
            oob = pos >= S
            wpe_pos = jnp.clip(pos, 0, cfg.max_seq - 1)
            x = (params["wte"][tokens] + params["wpe"][wpe_pos])
            x = x[None, :, :].astype(cfg.dtype)                # [1, C, d]
            safe = jnp.where(oob, 0, pos)
            bidx = jnp.where(oob, 0, table[safe // bs])
            off = jnp.where(oob, 0, pos % bs)
            # out-of-range rows write to a DUMMY context column (S) so
            # they cannot corrupt position 0 of the in-flight context;
            # each query row's mask is its own causal horizon, which
            # also excludes the dummy column for every real row
            wcol = jnp.where(oob, S, pos)
            mask = (jnp.arange(S + 1)[None, :] <= pos[:, None])  # [C, S+1]

            # pools are closed over, read per layer (slice + gather);
            # the chunk's K/V return as scan outputs and land in one
            # donated scatter — NOT carried through the scan, which
            # would copy the whole pool per chunk (see the step above)
            def layer(x, xs):
                lp, li = xs
                ck, cv = k_pool[li], v_pool[li]    # [N, h, bs, hd]
                y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
                qkv = jnp.einsum("bsd,de->bse", y,
                                 lp["wqkv"].astype(cfg.dtype))
                qkv = gpt._constrain(qkv, ("batch", "seq", "qkv"),
                                     mesh, rules)
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(t):                      # [1,C,d]->[1,h,C,hd]
                    return t.reshape(1, C, h, hd).transpose(0, 2, 1, 3)

                def gather(pool):                  # -> [1, h, S+1, hd]
                    g = pool[table]                # [T, h, bs, hd]
                    g = g.transpose(1, 0, 2, 3).reshape(h, S, hd)
                    return jnp.pad(g, [(0, 0), (0, 1), (0, 0)])[None]

                kh = k.reshape(C, h, hd).transpose(1, 0, 2)   # [h, C, hd]
                vh = v.reshape(C, h, hd).transpose(1, 0, 2)
                ctx_k = gather(ck).at[:, :, wcol, :].set(
                    kh.astype(ck.dtype))
                ctx_v = gather(cv).at[:, :, wcol, :].set(
                    vh.astype(cv.dtype))
                ctx_k = gpt._constrain(
                    ctx_k, ("batch", "heads", None, "kv"), mesh, rules)
                ctx_v = gpt._constrain(
                    ctx_v, ("batch", "heads", None, "kv"), mesh, rules)
                o = attention(heads(q), ctx_k, ctx_v, causal=False,
                              mask=mask[None, None], impl="reference")
                o = o.transpose(0, 2, 1, 3).reshape(1, C, cfg.d_model)
                o = jnp.einsum("bsd,de->bse", o,
                               lp["wo"].astype(cfg.dtype)) \
                    + lp["bo"].astype(cfg.dtype)
                x = x + o
                x = gpt._constrain(x, ("batch", "seq", "embed"),
                                   mesh, rules)
                y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
                dn = _mlp_block(y, lp, cfg, mesh, rules)
                return x + dn, (kh, vh)

            x, (ks, vs) = lax.scan(
                layer, x, (params["layers"], jnp.arange(L)))
            # ks/vs [L, h, C, hd] -> [C, L, h, hd] scatter through the
            # table (oob rows land in the scratch block)
            k_pool = k_pool.at[:, bidx, :, off, :].set(
                ks.transpose(2, 0, 1, 3).astype(k_pool.dtype))
            v_pool = v_pool.at[:, bidx, :, off, :].set(
                vs.transpose(2, 0, 1, 3).astype(v_pool.dtype))
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            logits = gpt._head(params, x, cfg, mesh, rules)[0]  # [C, V]
            return logits, k_pool, v_pool

        return chunk_fn

    return _cached(("chunk_prefill", bs, T, C), cfg, mesh, rules, build)


def make_spec_verify_step(cfg: GPTConfig, *, width: int, block_size: int,
                          n_table: int, mesh=None,
                          rules: Rules = DEFAULT_LLM_RULES):
    """jitted speculative VERIFY step: the paged decode step widened to
    score W = ``width`` positions per row in one call.

    (params, k_pool, v_pool [L, N, h, bs, hd], tables [b, T] int32,
     tokens [b, W] int32, positions [b] int32, active [b] bool,
     n_tokens [b] int32)
        -> (logits [b, W, vocab] f32, k_pool, v_pool)

    ``tokens[row, 0]`` is the row's current input token (sitting at
    ``positions[row]`` — exactly the plain step's input); columns 1..
    are drafted continuations at positions+1, +2, ...  ``n_tokens`` in
    [1, W] says how many leading columns are real; lanes past it (and
    all lanes of inactive rows) write to the scratch block / dummy
    context column and attend key 0 only, so their logits are garbage
    the caller ignores — never NaN, never corruption.

    Each real lane j's K/V is inserted into the gathered context at its
    own position and its query masked to keys <= positions[row]+j (the
    chunk-prefill causal-horizon mask batched over rows), so lane 0's
    logits are the plain decode step's logits and lane j's are exact
    next-token logits GIVEN the drafted prefix — greedy accept/reject
    on the host is therefore token-identical to non-speculative decode
    by construction.  All W positions land in ONE donated scatter;
    rejected lanes leave garbage K/V beyond the row's committed length,
    which the kv-length masks hide until decode overwrites it (same
    rule as prefill padding).

    Sharding and MoE follow the decode step: heads-sharded pools +
    per-device attention with one collective at the output projection,
    and gpt._moe_mlp expert dispatch over the W-lane window.
    """
    h, hd = cfg.n_heads, cfg.head_dim
    bs, W, T = int(block_size), int(width), int(n_table)
    S = T * bs

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def verify(params, k_pool, v_pool, tables, tokens, positions,
                   active, n_tokens):
            b = tokens.shape[0]
            L = k_pool.shape[0]
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            rows = jnp.arange(b)
            pos = positions[:, None] + jnp.arange(W, dtype=jnp.int32)  # [b,W]
            live = ((jnp.arange(W)[None, :] < n_tokens[:, None])
                    & active[:, None] & (pos < S))        # real lanes
            wpe_pos = jnp.clip(pos, 0, cfg.max_seq - 1)
            x = (params["wte"][tokens] + params["wpe"][wpe_pos])
            x = x.astype(cfg.dtype)                       # [b, W, d]
            safe = jnp.where(live, pos, 0)
            bidx = jnp.where(live, tables[rows[:, None], safe // bs], 0)
            off = jnp.where(live, pos % bs, 0)
            # dead lanes write a dummy context column (S — the first
            # slot of the appended SCRATCH-block table entry below);
            # every real query's causal horizon (<= S-1) excludes the
            # whole scratch region.  Appending a table column instead
            # of jnp.pad-ing the gathered context avoids a full-context
            # copy per layer per pool — the pad was ~half the verify
            # step's fixed cost.
            wcol = jnp.where(live, pos, S)
            hor = jnp.where(live, pos, 0)                 # >=1 key: no NaN
            tbl = jnp.concatenate(
                [tables, jnp.zeros((b, 1), tables.dtype)], axis=1)
            mask = (jnp.arange(S + bs)[None, None, :]
                    <= hor[:, :, None])[:, None]          # [b, 1, W, S+bs]

            # pools closed over, read per layer; the window's K/V come
            # back as scan outputs and land in one donated scatter (see
            # the plain step above for why they are not scan carries)
            def layer(x, xs):
                lp, li = xs
                ck, cv = k_pool[li], v_pool[li]    # [N, h, bs, hd]
                y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
                qkv = jnp.einsum("bsd,de->bse", y,
                                 lp["wqkv"].astype(cfg.dtype))
                qkv = gpt._constrain(qkv, ("batch", "seq", "qkv"),
                                     mesh, rules)
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(t):                      # [b,W,d]->[b,h,W,hd]
                    return t.reshape(b, W, h, hd).transpose(0, 2, 1, 3)

                def gather(pool):                  # -> [b, h, S+bs, hd]
                    g = pool[tbl]                  # [b, T+1, h, bs, hd]
                    return g.transpose(0, 2, 1, 3, 4).reshape(
                        b, h, S + bs, hd)

                kh = k.reshape(b, W, h, hd)
                vh = v.reshape(b, W, h, hd)
                # insert the window's K/V at their own positions in the
                # gathered context (position-major key order preserved;
                # dead lanes collide harmlessly in the dummy column)
                ctx_k = gather(ck).at[rows[:, None], :, wcol, :].set(
                    kh.astype(ck.dtype))
                ctx_v = gather(cv).at[rows[:, None], :, wcol, :].set(
                    vh.astype(cv.dtype))
                ctx_k = gpt._constrain(
                    ctx_k, ("batch", "heads", None, "kv"), mesh, rules)
                ctx_v = gpt._constrain(
                    ctx_v, ("batch", "heads", None, "kv"), mesh, rules)
                o = attention(heads(q), ctx_k, ctx_v, causal=False,
                              mask=mask, impl="reference")
                o = o.transpose(0, 2, 1, 3).reshape(b, W, cfg.d_model)
                o = jnp.einsum("bsd,de->bse", o,
                               lp["wo"].astype(cfg.dtype)) \
                    + lp["bo"].astype(cfg.dtype)
                x = x + o
                x = gpt._constrain(x, ("batch", "seq", "embed"),
                                   mesh, rules)
                y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
                dn = _mlp_block(y, lp, cfg, mesh, rules)
                return x + dn, (kh, vh)

            x, (ks, vs) = lax.scan(
                layer, x, (params["layers"], jnp.arange(L)))
            # ks/vs [L, b, W, h, hd] -> [b, W, L, h, hd]: ONE scatter
            # commits every lane's K/V through the table (dead lanes
            # hit the scratch block)
            k_pool = k_pool.at[:, bidx, :, off, :].set(
                ks.transpose(1, 2, 0, 3, 4).astype(k_pool.dtype))
            v_pool = v_pool.at[:, bidx, :, off, :].set(
                vs.transpose(1, 2, 0, 3, 4).astype(v_pool.dtype))
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            logits = gpt._head(params, x, cfg, mesh, rules)  # [b, W, V]
            return logits, k_pool, v_pool

        return verify

    return _cached(("spec_verify", bs, T, W), cfg, mesh, rules, build)


def make_paged_draft_step(cfg: GPTConfig, *, draft_layers: int, k: int,
                          block_size: int, n_table: int, mesh=None,
                          rules: Rules = DEFAULT_LLM_RULES):
    """jitted truncated-layer SELF-DRAFT burst: ``k`` autoregressive
    draft tokens per row in ONE compiled call — a ``lax.scan`` over
    draft positions, each scanning only the first ``draft_layers``
    layers, then the head and a greedy argmax feeding the next step.

    (params, k_pool, v_pool [L, N, h, bs, hd], tables [b, T] int32,
     tokens [b] int32, positions [b] int32, want [b] int32)
        -> (drafts [b, k] int32, k_pool, v_pool)

    Row r drafts ``want[r]`` tokens (0 = the row sits the burst out);
    columns past ``want[r]`` are garbage the caller ignores.  Fusing
    the whole burst kills the k host round-trips of a step-at-a-time
    loop — on small models the dispatch + logits transfer per step
    costs as much as the truncated forward itself.

    The burst's K/V cannot go through the pool between steps (one
    donated scatter at the end, same discipline as every other step
    body), so step j's attention reads earlier burst tokens from a
    carried side-buffer inserted into the gathered context at their
    true positions — the verify step's scratch-column trick, batched
    over the burst window.  Only layers < draft_layers land in the
    pool, and those K/V are bit-identical to the full model's at the
    same (layer, position) because layer l depends only on layers
    below it, so drafting straight through the REAL pool is safe:
    committed positions are unchanged, and the verify pass rewrites
    every drafted position at all layers regardless of the accept
    outcome.  Cost per draft token ~ draft_layers / n_layers of a full
    step, with zero extra weights.

    Sharding and MoE follow the decode step (heads-sharded pools,
    gpt._moe_mlp dispatch per draft token); the truncated-layer trunk
    slice composes with MoE leaves because tree_map slices every
    per-layer leaf, expert weights included.
    """
    h, hd, bs = cfg.n_heads, cfg.head_dim, int(block_size)
    D, K, T = int(draft_layers), int(k), int(n_table)
    S = T * bs
    if not (1 <= D < cfg.n_layers):
        raise SpeculationUnsupported(
            f"draft_layers must be in [1, n_layers) = [1, "
            f"{cfg.n_layers}), got {D}")
    if K < 1:
        raise SpeculationUnsupported(f"draft burst k must be >= 1, "
                                     f"got {K}")

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def draft(params, k_pool, v_pool, tables, tokens, positions,
                  want):
            b = tokens.shape[0]
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            rows = jnp.arange(b)
            lanes = jnp.arange(K, dtype=jnp.int32)
            # one scratch table column (id 0 = the pool's scratch
            # block): dead lanes write context column S, which every
            # live query's kv-length horizon (<= S) can include only
            # as its own position — see wcol below
            tbl = jnp.concatenate(
                [tables, jnp.zeros((b, 1), tables.dtype)], axis=1)

            def step(carry, j):
                cur, pos, bk, bv = carry          # bk/bv [D, b, K, h, hd]
                live = (want > j) & (pos < S)
                x = (params["wte"][cur]
                     + params["wpe"][jnp.clip(pos, 0, cfg.max_seq - 1)])
                x = x[:, None, :].astype(cfg.dtype)           # [b, 1, d]
                # burst columns: token i of the burst sits at
                # positions0 + i; steps not yet drafted (i >= j) and
                # dead rows land in the scratch column S
                bpos = (pos - j)[:, None] + lanes[None, :]    # [b, K]
                bvalid = (lanes[None, :] <= j) & live[:, None] \
                    & (bpos < S)
                wcol = jnp.where(bvalid, bpos, S)
                kv_len = jnp.where(live, pos + 1, 1)

                def layer(x, xs):
                    lp, li, bk_l, bv_l = xs
                    ck, cv = k_pool[li], v_pool[li]
                    y = gpt._layer_norm(x, lp["ln1_scale"],
                                        lp["ln1_bias"])
                    qkv = jnp.einsum("bsd,de->bse", y,
                                     lp["wqkv"].astype(cfg.dtype))
                    qkv = gpt._constrain(qkv, ("batch", "seq", "qkv"),
                                         mesh, rules)
                    q, kk, v = jnp.split(qkv, 3, axis=-1)

                    def heads(t):                  # [b,1,d]->[b,h,1,hd]
                        return t.reshape(b, 1, h, hd).transpose(
                            0, 2, 1, 3)

                    def gather(pool):              # -> [b, h, S+bs, hd]
                        g = pool[tbl]              # [b, T+1, h, bs, hd]
                        return g.transpose(0, 2, 1, 3, 4).reshape(
                            b, h, S + bs, hd)

                    kh = kk.reshape(b, h, hd)
                    vh = v.reshape(b, h, hd)
                    # current token joins the burst buffer, then the
                    # whole window is inserted at its true positions —
                    # steps < j come from the carry, the pool knows
                    # nothing of the burst yet
                    bk_l = bk_l.at[:, j].set(kh.astype(bk_l.dtype))
                    bv_l = bv_l.at[:, j].set(vh.astype(bv_l.dtype))
                    ctx_k = gather(ck).at[rows[:, None], :, wcol, :] \
                        .set(bk_l)
                    ctx_v = gather(cv).at[rows[:, None], :, wcol, :] \
                        .set(bv_l)
                    ctx_k = gpt._constrain(
                        ctx_k, ("batch", "heads", None, "kv"),
                        mesh, rules)
                    ctx_v = gpt._constrain(
                        ctx_v, ("batch", "heads", None, "kv"),
                        mesh, rules)
                    o = attention(heads(q), ctx_k, ctx_v, causal=False,
                                  kv_lengths=kv_len, impl="reference")
                    o = o.transpose(0, 2, 1, 3).reshape(
                        b, 1, cfg.d_model)
                    o = jnp.einsum("bsd,de->bse", o,
                                   lp["wo"].astype(cfg.dtype)) \
                        + lp["bo"].astype(cfg.dtype)
                    x = x + o
                    x = gpt._constrain(x, ("batch", "seq", "embed"),
                                       mesh, rules)
                    y = gpt._layer_norm(x, lp["ln2_scale"],
                                        lp["ln2_bias"])
                    dn = _mlp_block(y, lp, cfg, mesh, rules)
                    return x + dn, (bk_l, bv_l)

                trunk = jax.tree_util.tree_map(lambda a: a[:D],
                                               params["layers"])
                x, (bk, bv) = lax.scan(
                    layer, x, (trunk, jnp.arange(D), bk, bv))
                logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                cur = jnp.where(live, nxt, cur)
                pos = pos + live.astype(jnp.int32)
                return (cur, pos, bk, bv), nxt

            bk0 = jnp.zeros((D, b, K, h, hd), cfg.dtype)
            (_, _, bk, bv), toks = lax.scan(
                step, (tokens, positions, bk0, bk0), jnp.arange(K))
            # ONE donated scatter commits the whole burst's K/V for
            # layers < D (dead lanes collide harmlessly in the scratch
            # block); layers >= D keep their committed content
            bpos = positions[:, None] + lanes[None, :]        # [b, K]
            valid = (lanes[None, :] < want[:, None]) & (bpos < S)
            safe = jnp.where(valid, bpos, 0)
            bidx = jnp.where(valid, tbl[rows[:, None], safe // bs], 0)
            off = jnp.where(valid, safe % bs, 0)
            # update layout [b*K, D, h, hd]: the two advanced indices
            # (block, offset) are separated by sliced dims, so their
            # broadcast axis leads
            flat = lambda a: a.transpose(1, 2, 0, 3, 4).reshape(
                b * K, D, h, hd)
            k_pool = k_pool.at[:D, bidx.reshape(-1), :,
                               off.reshape(-1), :].set(
                flat(bk).astype(k_pool.dtype))
            v_pool = v_pool.at[:D, bidx.reshape(-1), :,
                               off.reshape(-1), :].set(
                flat(bv).astype(v_pool.dtype))
            k_pool = gpt._constrain(k_pool, POOL_AXES, mesh, rules)
            v_pool = gpt._constrain(v_pool, POOL_AXES, mesh, rules)
            return toks.T, k_pool, v_pool     # drafts [b, K]

        return draft

    return _cached(("draft_burst", bs, T, D, K), cfg, mesh,
                   rules, build)


def ngram_propose(context: np.ndarray, k: int,
                  max_ngram: int = 3) -> np.ndarray:
    """Prompt-lookup draft proposal (Saxena 2023): find the most recent
    EARLIER occurrence of the context's trailing n-gram (longest n
    first, n <= max_ngram) and propose up to ``k`` of the tokens that
    followed it.  Host-side, zero weights — the drafter for workloads
    whose generations echo their own prompt/history (shared-prefix
    serving, repetitive greedy tails).  Returns an empty array when
    nothing matches; the engine then decodes that row plainly."""
    n = int(len(context))
    if n < 2 or k < 1:
        return np.empty(0, np.int32)
    context = np.asarray(context, np.int32)
    for m in range(min(int(max_ngram), n - 1), 0, -1):
        pat = context[n - m:]
        # candidate starts s in [0, n-m-1]: the trailing n-gram itself
        # (s = n-m) is excluded, and every match has >= 1 follower
        win = np.stack([context[i:n - m + i] for i in range(m)], axis=1)
        hits = np.flatnonzero((win == pat).all(axis=1))
        if hits.size == 0:
            continue
        s = int(hits[-1])                 # most recent occurrence
        prop = context[s + m:s + m + k]
        if prop.size:
            return prop.astype(np.int32)
    return np.empty(0, np.int32)


def clear_fn_cache() -> None:
    """Drop the shared compiled-function cache (tests / benchmarks that
    want cold-compile timings)."""
    _FN_CACHE.clear()
