"""Incremental (KV-cache) decode for the GPT: paged decode + chunked
prefill (production), slot decode + full prefill (legacy baseline).

All programs have STATIC shapes so each compiles exactly once
regardless of request mix — and (no-mesh path) once per (config,
rules, geometry) across ALL engines, so a fleet scaling out replicas
or multiplexing model variants reuses the compiled set instead of
paying a per-engine recompile.

Paged path (cache.BlockPool):

  * chunk_prefill — a fixed-width window of the prompt ([C] tokens at
    positions start..start+C) runs one forward layer-by-layer against
    the BLOCK POOL: each layer writes the window's K/V through the
    block table, then attends over the gathered table (earlier chunks'
    K/V included), each query row masked to its OWN causal horizon.
    Long prompts therefore prefill as a sequence of bounded-cost steps
    the engine interleaves with decode iterations — a long prompt
    stops stalling neighbors' token cadence.
  * paged_decode_step — one token for EVERY row at once; the cache
    write is a per-row (block, offset) scatter into the pool (inactive
    rows redirected to the scratch block), attention gathers each
    row's block table and masks to its valid prefix
    (ops/attention.paged_attention).

Legacy slot path (cache.KVCacheManager, engine ``paged=False``):

  * prefill — the ordinary training forward with ``return_kv=True``
    (models/gpt.py) over the prompt padded to the cache width.
  * decode_step — one-hot scatter on the position axis of the
    ``[L, n_slots, h, S, hd]`` cache, per-row kv_lengths masking.

All step bodies mirror gpt._transformer_layer's einsums exactly (dense
MLP path); greedy token-parity with full-recompute ``generate()`` is
pinned by tests/test_inference.py + tests/test_paged_cache.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models import gpt
from ray_tpu.models.gpt import GPTConfig
from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules


class MoEDecodeUnsupported(NotImplementedError):
    """The inference engine has no MoE decode path (expert dispatch per
    cached token — ROADMAP 1c).  Typed so the gap fails EARLY and
    clearly — at engine construction / admission time, never mid-decode
    with slots already held — and so callers can distinguish the known
    capability gap from a generic failure."""

    def __init__(self, cfg: GPTConfig):
        super().__init__(
            f"the inference engine has no MoE decode path yet "
            f"(n_experts={cfg.n_experts}: expert dispatch per cached "
            f"token is unimplemented — ROADMAP 1c); serve this config "
            f"with a dense MLP (n_experts=0) or the training forward")

# engines with the same (cfg, rules) on the default (no-mesh) path share
# ONE jitted prefill/step pair: the compiled programs are stateless
# (params/cache are arguments; donation is per-call), and a fleet of N
# replicas x M model variants would otherwise pay N*M identical
# compilations — a multi-second head-of-line stall every time the
# autoscaler grows or the multiplexer loads a variant.  Meshed engines
# skip the cache (mesh identity isn't a safe dict key across tests).
_FN_CACHE: dict = {}


def _cached(kind: str, cfg: GPTConfig, mesh, rules, build):
    if mesh is not None:
        return build()
    key = (kind, cfg, rules if isinstance(rules, tuple) else id(rules))
    fn = _FN_CACHE.get(key)
    if fn is None:
        fn = _FN_CACHE[key] = build()
    return fn


def make_prefill_fn(cfg: GPTConfig, *, mesh=None,
                    rules: Rules = DEFAULT_LLM_RULES):
    """jitted (params, tokens [b, S]) -> (logits [b, S, V], k, v
    [L, b, h, S, hd] each)."""
    if cfg.n_experts:
        raise MoEDecodeUnsupported(cfg)

    def build():
        @jax.jit
        def prefill(params, tokens):
            logits, (k, v) = gpt.forward(params, tokens, cfg, mesh=mesh,
                                         rules=rules, return_kv=True)
            return logits, k, v
        return prefill

    return _cached("prefill", cfg, mesh, rules, build)


def make_decode_step(cfg: GPTConfig, *, mesh=None,
                     rules: Rules = DEFAULT_LLM_RULES):
    """jitted one-token step over the whole slot batch.

    (params, k_cache, v_cache [L, b, h, S, hd], tokens [b] int32,
     positions [b] int32, active [b] bool)
        -> (logits [b, vocab] f32, k_cache, v_cache)

    ``tokens`` are the slots' current input tokens, each sitting at
    ``positions[slot]``; the step writes that token's K/V into the cache
    (masked by ``active`` so parked slots stay untouched), attends over
    positions [0, positions[slot]] and returns next-token logits.
    """
    if cfg.n_experts:
        raise MoEDecodeUnsupported(cfg)
    h, hd = cfg.n_heads, cfg.head_dim

    def build():
        return _make_step(cfg, mesh, rules, h, hd)

    return _cached("step", cfg, mesh, rules, build)


def _make_step(cfg, mesh, rules, h, hd):
    @partial(jax.jit, donate_argnums=(1, 2))
    def step(params, k_cache, v_cache, tokens, positions, active):
        b = tokens.shape[0]
        S = k_cache.shape[3]
        x = (params["wte"][tokens] + params["wpe"][positions])
        x = x[:, None, :].astype(cfg.dtype)               # [b, 1, d]
        # one-hot write mask on the position axis, zeroed for parked slots
        write = ((jnp.arange(S)[None, :] == positions[:, None])
                 & active[:, None])                       # [b, S]
        kv_len = jnp.where(active, positions + 1, 1)      # >=1: no NaN rows

        def layer(x, xs):
            lp, ck, cv = xs                               # ck/cv [b,h,S,hd]
            y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
            qkv = jnp.einsum("bsd,de->bse", y,
                             lp["wqkv"].astype(cfg.dtype))
            q, k, v = jnp.split(qkv, 3, axis=-1)

            def heads(t):                                 # [b,1,d]->[b,h,1,hd]
                return t.reshape(b, 1, h, hd).transpose(0, 2, 1, 3)

            kh, vh = heads(k), heads(v)                   # [b, h, 1, hd]
            ck = jnp.where(write[:, None, :, None], kh, ck)
            cv = jnp.where(write[:, None, :, None], vh, cv)
            o = attention(heads(q), ck, cv, causal=False,
                          kv_lengths=kv_len, impl="reference")
            o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.d_model)
            o = jnp.einsum("bsd,de->bse", o, lp["wo"].astype(cfg.dtype)) \
                + lp["bo"].astype(cfg.dtype)
            x = x + o
            y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
            u = jnp.einsum("bsd,df->bsf", y,
                           lp["w_up"].astype(cfg.dtype)) \
                + lp["b_up"].astype(cfg.dtype)
            u = jax.nn.gelu(u)
            dn = jnp.einsum("bsf,fd->bsd", u,
                            lp["w_down"].astype(cfg.dtype)) \
                + lp["b_down"].astype(cfg.dtype)
            return x + dn, (ck, cv)

        x, (k_cache, v_cache) = lax.scan(
            layer, x, (params["layers"], k_cache, v_cache))
        logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
        return logits, k_cache, v_cache

    return step


# ---------------------------------------------------------------------------
# paged path


def make_paged_decode_step(cfg: GPTConfig, *, block_size: int,
                           n_table: int, mesh=None,
                           rules: Rules = DEFAULT_LLM_RULES):
    """jitted one-token step over the whole row batch, block-pool cache.

    (params, k_pool, v_pool [L, N, h, bs, hd], tables [b, T] int32,
     tokens [b] int32, positions [b] int32, active [b] bool)
        -> (logits [b, vocab] f32, k_pool, v_pool)

    Each row's current token K/V scatters into the pool at
    ``(tables[row, pos // bs], pos % bs)`` — inactive rows are
    redirected to the scratch block (id 0) so the scatter needs no
    conditional — and attention gathers the row's table, masked to its
    valid prefix (ops/attention.paged_attention).  Tail blocks are
    per-row exclusive (the engine copy-on-writes shared tails before
    the step), so active rows never collide in the scatter.
    """
    if cfg.n_experts:
        raise MoEDecodeUnsupported(cfg)
    h, hd, bs = cfg.n_heads, cfg.head_dim, int(block_size)

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def step(params, k_pool, v_pool, tables, tokens, positions,
                 active):
            b = tokens.shape[0]
            L = k_pool.shape[0]
            T = tables.shape[1]
            x = (params["wte"][tokens] + params["wpe"][positions])
            x = x[:, None, :].astype(cfg.dtype)               # [b, 1, d]
            rows = jnp.arange(b)
            bidx = jnp.where(active, tables[rows, positions // bs], 0)
            off = jnp.where(active, positions % bs, 0)
            kv_len = jnp.where(active, positions + 1, 1)      # >=1: no NaN

            # the pools are CLOSED OVER by the scan body and read with a
            # per-layer dynamic slice + table gather; the new K/V come
            # back as stacked scan outputs and land in ONE donated
            # scatter after the scan.  (Carrying the pools through the
            # scan as xs/ys — the obvious formulation — copies the
            # ENTIRE pool every call, a fixed ~2x-pool-bytes tax per
            # decode step that dwarfs the actual compute.)
            def layer(x, xs):
                lp, li = xs
                ck, cv = k_pool[li], v_pool[li]    # [N, h, bs, hd]
                y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
                qkv = jnp.einsum("bsd,de->bse", y,
                                 lp["wqkv"].astype(cfg.dtype))
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(t):                      # [b,1,d]->[b,h,1,hd]
                    return t.reshape(b, 1, h, hd).transpose(0, 2, 1, 3)

                def gather(pool):                  # -> [b, h, S, hd]
                    g = pool[tables]               # [b, T, h, bs, hd]
                    return g.transpose(0, 2, 1, 3, 4).reshape(
                        b, h, T * bs, hd)

                kh = k.reshape(b, h, hd)
                vh = v.reshape(b, h, hd)
                # insert the current token's K/V at its own position in
                # the gathered context — key ORDER stays position-major,
                # so the masked softmax is numerically identical to the
                # write-then-gather formulation (and to the slot step)
                ctx_k = gather(ck).at[rows, :, positions, :].set(
                    kh.astype(ck.dtype))
                ctx_v = gather(cv).at[rows, :, positions, :].set(
                    vh.astype(cv.dtype))
                o = attention(heads(q), ctx_k, ctx_v, causal=False,
                              kv_lengths=kv_len, impl="reference")
                o = o.transpose(0, 2, 1, 3).reshape(b, 1, cfg.d_model)
                o = jnp.einsum("bsd,de->bse", o,
                               lp["wo"].astype(cfg.dtype)) \
                    + lp["bo"].astype(cfg.dtype)
                x = x + o
                y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
                u = jnp.einsum("bsd,df->bsf", y,
                               lp["w_up"].astype(cfg.dtype)) \
                    + lp["b_up"].astype(cfg.dtype)
                u = jax.nn.gelu(u)
                dn = jnp.einsum("bsf,fd->bsd", u,
                                lp["w_down"].astype(cfg.dtype)) \
                    + lp["b_down"].astype(cfg.dtype)
                return x + dn, (kh, vh)

            x, (ks, vs) = lax.scan(
                layer, x, (params["layers"], jnp.arange(L)))
            # ks/vs [L, b, h, hd] -> one in-place scatter on the donated
            # pools at each row's (block, offset); inactive rows hit the
            # scratch block
            k_pool = k_pool.at[:, bidx, :, off, :].set(
                ks.transpose(1, 0, 2, 3).astype(k_pool.dtype))
            v_pool = v_pool.at[:, bidx, :, off, :].set(
                vs.transpose(1, 0, 2, 3).astype(v_pool.dtype))
            logits = gpt._head(params, x, cfg, mesh, rules)[:, 0, :]
            return logits, k_pool, v_pool

        return step

    return _cached(("paged_step", bs, int(n_table)), cfg, mesh, rules,
                   build)


def make_chunk_prefill_fn(cfg: GPTConfig, *, chunk: int, block_size: int,
                          n_table: int, mesh=None,
                          rules: Rules = DEFAULT_LLM_RULES):
    """jitted fixed-width prefill chunk against the block pool.

    (params, k_pool, v_pool [L, N, h, bs, hd], table [T] int32,
     tokens [C] int32, start int32)
        -> (logits [C, vocab] f32, k_pool, v_pool)

    Processes prompt positions ``start .. start+C``: each layer writes
    the window's K/V through the block table (rows past the table's
    span are redirected to the scratch block), then attends over the
    gathered table with each query row masked to its OWN causal horizon
    (key position <= query position) — so earlier chunks' cached K/V,
    including an adopted prefix from the radix index, participates
    exactly as in a full forward.  Pad rows past the prompt compute
    garbage that lands in masked positions and is overwritten by
    decode; the caller reads only the rows it needs.  The engine
    interleaves one chunk per scheduler pass with decode iterations
    (chunked prefill: bounded prefill cost per token cadence).
    """
    if cfg.n_experts:
        raise MoEDecodeUnsupported(cfg)
    h, hd = cfg.n_heads, cfg.head_dim
    bs, C, T = int(block_size), int(chunk), int(n_table)
    S = T * bs

    def build():
        @partial(jax.jit, donate_argnums=(1, 2))
        def chunk_fn(params, k_pool, v_pool, table, tokens, start):
            L = k_pool.shape[0]
            pos = start + jnp.arange(C, dtype=jnp.int32)       # [C]
            oob = pos >= S
            wpe_pos = jnp.clip(pos, 0, cfg.max_seq - 1)
            x = (params["wte"][tokens] + params["wpe"][wpe_pos])
            x = x[None, :, :].astype(cfg.dtype)                # [1, C, d]
            safe = jnp.where(oob, 0, pos)
            bidx = jnp.where(oob, 0, table[safe // bs])
            off = jnp.where(oob, 0, pos % bs)
            # out-of-range rows write to a DUMMY context column (S) so
            # they cannot corrupt position 0 of the in-flight context;
            # each query row's mask is its own causal horizon, which
            # also excludes the dummy column for every real row
            wcol = jnp.where(oob, S, pos)
            mask = (jnp.arange(S + 1)[None, :] <= pos[:, None])  # [C, S+1]

            # pools are closed over, read per layer (slice + gather);
            # the chunk's K/V return as scan outputs and land in one
            # donated scatter — NOT carried through the scan, which
            # would copy the whole pool per chunk (see the step above)
            def layer(x, xs):
                lp, li = xs
                ck, cv = k_pool[li], v_pool[li]    # [N, h, bs, hd]
                y = gpt._layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
                qkv = jnp.einsum("bsd,de->bse", y,
                                 lp["wqkv"].astype(cfg.dtype))
                q, k, v = jnp.split(qkv, 3, axis=-1)

                def heads(t):                      # [1,C,d]->[1,h,C,hd]
                    return t.reshape(1, C, h, hd).transpose(0, 2, 1, 3)

                def gather(pool):                  # -> [1, h, S+1, hd]
                    g = pool[table]                # [T, h, bs, hd]
                    g = g.transpose(1, 0, 2, 3).reshape(h, S, hd)
                    return jnp.pad(g, [(0, 0), (0, 1), (0, 0)])[None]

                kh = k.reshape(C, h, hd).transpose(1, 0, 2)   # [h, C, hd]
                vh = v.reshape(C, h, hd).transpose(1, 0, 2)
                ctx_k = gather(ck).at[:, :, wcol, :].set(
                    kh.astype(ck.dtype))
                ctx_v = gather(cv).at[:, :, wcol, :].set(
                    vh.astype(cv.dtype))
                o = attention(heads(q), ctx_k, ctx_v, causal=False,
                              mask=mask[None, None], impl="reference")
                o = o.transpose(0, 2, 1, 3).reshape(1, C, cfg.d_model)
                o = jnp.einsum("bsd,de->bse", o,
                               lp["wo"].astype(cfg.dtype)) \
                    + lp["bo"].astype(cfg.dtype)
                x = x + o
                y = gpt._layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
                u = jnp.einsum("bsd,df->bsf", y,
                               lp["w_up"].astype(cfg.dtype)) \
                    + lp["b_up"].astype(cfg.dtype)
                u = jax.nn.gelu(u)
                dn = jnp.einsum("bsf,fd->bsd", u,
                                lp["w_down"].astype(cfg.dtype)) \
                    + lp["b_down"].astype(cfg.dtype)
                return x + dn, (kh, vh)

            x, (ks, vs) = lax.scan(
                layer, x, (params["layers"], jnp.arange(L)))
            # ks/vs [L, h, C, hd] -> [C, L, h, hd] scatter through the
            # table (oob rows land in the scratch block)
            k_pool = k_pool.at[:, bidx, :, off, :].set(
                ks.transpose(2, 0, 1, 3).astype(k_pool.dtype))
            v_pool = v_pool.at[:, bidx, :, off, :].set(
                vs.transpose(2, 0, 1, 3).astype(v_pool.dtype))
            logits = gpt._head(params, x, cfg, mesh, rules)[0]  # [C, V]
            return logits, k_pool, v_pool

        return chunk_fn

    return _cached(("chunk_prefill", bs, T, C), cfg, mesh, rules, build)


def clear_fn_cache() -> None:
    """Drop the shared compiled-function cache (tests / benchmarks that
    want cold-compile timings)."""
    _FN_CACHE.clear()
