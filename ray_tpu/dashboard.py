"""Web dashboard: cluster state over HTTP.

Reference capability: the Ray dashboard (reference: dashboard/ — node /
actor / job / object views over the state APIs).  Dependency-free shape:
one ThreadingHTTPServer serving a static single-page UI plus JSON
endpoints backed by observer connections to a node service (the same
read-only protocol the CLI uses), so it can point at ANY live cluster.

Run: ``python -m ray_tpu dashboard --address <node> [--port 8265]``.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.4rem; }
table { border-collapse: collapse; min-width: 40rem; }
th, td { text-align: left; padding: .25rem .7rem; border-bottom:
  1px solid #ddd; font-size: .85rem; }
th { background: #f5f5f5; }
.ok { color: #0a7d36; } .bad { color: #c0392b; }
#updated { color: #888; font-size: .8rem; }
</style></head><body>
<h1>ray_tpu dashboard</h1><div id="updated"></div>
<h2>History</h2><canvas id="spark" width="900" height="90"
  style="border:1px solid #ddd"></canvas>
<div id="sparklegend" style="font-size:.8rem;color:#666"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Resources</h2><table id="resources"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Task summary</h2><table id="tasks"></table>
<h2>Recent tasks</h2><table id="taskdetail"></table>
<div id="taskevents" style="display:none"><h2>Task events:
<span id="taskid"></span></h2><table id="events"></table></div>
<h2>Workers</h2><table id="workers"></table>
<pre id="text" style="background:#f8f8f8;border:1px solid #ddd;
padding:.6rem;max-height:24rem;overflow:auto;display:none"></pre>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Object store</h2><table id="objects"></table>
<script>
function esc(v) {
  // cluster-supplied strings (names, entrypoints) are untrusted —
  // escape everything; trusted markup opts in via {html: "..."}
  if (v && typeof v === "object" && "html" in v) return v.html;
  return String(v).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${esc(c)}</${tag||"td"}>`)
    .join("") + "</tr>";
}
function fill(id, header, rows) {
  document.getElementById(id).innerHTML =
    row(header, "th") + rows.map(r => row(r)).join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    fill("nodes", ["node", "address", "alive", "total", "available",
                   "queued"],
      s.nodes.map(n => [n.node_id.slice(0, 12), n.address,
        n.alive ? {html: '<span class="ok">alive</span>'}
                : {html: '<span class="bad">dead</span>'},
        JSON.stringify(n.resources), JSON.stringify(n.available),
        JSON.stringify(n.queued || {})]));
    fill("resources", ["resource", "available", "total"],
      Object.keys(s.resources.total).map(k =>
        [k, s.resources.available[k] ?? 0, s.resources.total[k]]));
    fill("actors", ["actor", "class", "name", "state"],
      s.actors.map(a => [a.actor_id.slice(0, 12), a.class_name,
                         a.name || "-", a.state]));
    fill("tasks", ["function", "states"],
      Object.entries(s.tasks.cluster).map(([k, v]) =>
        [k, JSON.stringify(v)]));
    fill("jobs", ["job", "status", "entrypoint"],
      s.jobs.map(j => [j.job_id, j.status, j.entrypoint]));
    fill("objects", ["metric", "value"],
      Object.entries(s.object_store).map(([k, v]) => [k, v]));
    fill("taskdetail", ["task", "name", "state", "duration", ""],
      s.recent_tasks.map(t => [t.task_id.slice(0, 12), t.name, t.state,
        t.duration == null ? "-" : t.duration.toFixed(3) + "s",
        {html: `<a href="#" onclick="events('${esc(t.task_id)}');` +
               `return false">events</a>`}]));
    fill("workers", ["worker", "kind", "pid", "state", "", ""],
      s.workers.map(w => [w.worker_id.slice(0, 18), w.kind, w.pid,
        w.state,
        {html: `<a href="#" onclick="stack(${w.pid});return false">` +
               `stack</a>`},
        {html: w.log
          ? `<a href="#" onclick="logs('${esc(w.log)}');return false">` +
            `logs</a>` : "-"}]));
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed: " + e;
  }
}
async function events(tid) {
  const ev = await (await fetch("/api/tasks/" + tid)).json();
  document.getElementById("taskevents").style.display = "";
  document.getElementById("taskid").textContent = tid.slice(0, 16);
  fill("events", ["state", "worker", "time"],
    ev.events.map(e => [e.state, e.worker ?? "-",
      new Date(e.time * 1000).toLocaleTimeString()]));
}
async function showText(url) {
  const r = await (await fetch(url)).json();
  const el = document.getElementById("text");
  el.style.display = "";
  el.textContent = r.error ? ("error: " + r.error)
    : (r.data ?? JSON.stringify(r, null, 1));
}
function stack(pid) { showText("/api/stack?pid=" + pid); }
function logs(name) {
  showText("/api/logs" + (name ? "?name=" + encodeURIComponent(name) : ""));
}
const SPARK = [["cpu_used", "#e4593b"], ["tasks_running", "#2f6db3"],
               ["store_used_mb", "#0a7d36"]];
async function sparkline() {
  const hist = await (await fetch("/api/metrics/history")).json();
  const c = document.getElementById("spark");
  const ctx = c.getContext("2d");
  ctx.clearRect(0, 0, c.width, c.height);
  if (!hist.length) return;
  let legend = [];
  for (const [key, color] of SPARK) {
    const vals = hist.map(h => h[key] ?? 0);
    const max = Math.max(...vals, 1e-9);
    ctx.strokeStyle = color; ctx.beginPath();
    vals.forEach((v, i) => {
      const x = i / Math.max(vals.length - 1, 1) * (c.width - 4) + 2;
      const y = c.height - 4 - v / max * (c.height - 8);
      i ? ctx.lineTo(x, y) : ctx.moveTo(x, y);
    });
    ctx.stroke();
    legend.push(`<span style="color:${color}">&#9632;</span> ` +
                `${key} (now ${vals[vals.length-1].toFixed(1)}, ` +
                `max ${max.toFixed(1)})`);
  }
  document.getElementById("sparklegend").innerHTML = legend.join(" · ");
}
refresh(); setInterval(refresh, 2000);
sparkline(); setInterval(sparkline, 5000);
</script></body></html>
"""


class _StateSource:
    """Observer-protocol reads against a node service (one short-lived
    connection per snapshot — read-only, no runtime needed; shared wire
    implementation with the CLI, error replies raise)."""

    def __init__(self, address: str):
        self.address = address

    def _request_many(self, queries: list[dict],
                      timeout: float = 30.0) -> list[dict]:
        from ray_tpu.core.observer import observer_query
        return observer_query(self.address, queries,
                              request_timeout=timeout)

    def summary(self) -> dict:
        from ray_tpu.util.state import group_counts
        replies = self._request_many([
            {"t": "state", "what": "nodes"},
            {"t": "state", "what": "resources"},
            {"t": "state", "what": "cluster_actors"},
            {"t": "state", "what": "actors"},
            {"t": "state", "what": "tasks"},
            {"t": "object_stats"},
            {"t": "kv_keys", "prefix": b"job:"},
            {"t": "state", "what": "workers"},
        ])
        (nodes, res, cactors, lactors, tasks, ostats, jkeys,
         workers) = replies
        actors = cactors["data"] or lactors["data"]
        jobs = []
        job_keys = [k for k in jkeys.get("keys", [])
                    if not k.endswith(b":logs")]
        if job_keys:
            job_replies = self._request_many(
                [{"t": "kv_get", "key": k} for k in job_keys])
            for r in job_replies:
                if r.get("value"):
                    try:
                        jobs.append(json.loads(r["value"]))
                    except Exception:
                        pass
        recent = sorted(tasks["data"],
                        key=lambda t: t.get("submitted_at") or 0,
                        reverse=True)[:50]
        return {
            "nodes": nodes["data"],
            "resources": res["data"],
            "actors": actors,
            "tasks": group_counts(tasks["data"], "name"),
            "recent_tasks": recent,
            "workers": workers["data"],
            "object_store": ostats["stats"],
            "jobs": jobs,
            "time": time.time(),
        }

    def task_events(self, task_id_hex: str) -> dict:
        """Drill-down: the per-task state timeline (reference: the
        dashboard's task detail view over task events)."""
        (reply,) = self._request_many(
            [{"t": "state", "what": "task_events"}])
        events = [e for e in reply["data"]
                  if e.get("task_id") == task_id_hex]
        return {"task_id": task_id_hex, "events": events}

    def worker_logs(self, name: Optional[str] = None) -> dict:
        q = {"t": "worker_logs"}
        if name:
            q["name"] = name
        try:
            (reply,) = self._request_many([q])
        except RuntimeError as e:      # error replies raise in observer
            return {"error": str(e)}
        if name:
            return {"name": name, "data": reply.get("data")}
        files = reply.get("files", [])
        return {"files": files,
                "data": "\n".join(f"{f['name']}\t{f['size']}B"
                                  for f in files)}

    def stack_dump(self, pid: int) -> dict:
        try:
            (reply,) = self._request_many(
                [{"t": "stack_dump", "pid": pid}])
        except RuntimeError as e:
            return {"pid": pid, "error": str(e)}
        return {"pid": pid, "data": reply.get("data"),
                "log": reply.get("log")}

    def metrics_sample(self) -> dict:
        """One lightweight point for the history ring (reference:
        dashboard/modules/metrics timeseries — here self-contained, no
        Prometheus/Grafana dependency)."""
        res, ostats, tasks = self._request_many([
            {"t": "state", "what": "resources"},
            {"t": "object_stats"},
            {"t": "state", "what": "tasks"},
        ])
        data = res.get("data") or {"total": {}, "available": {}}
        total = data.get("total", {})
        avail = data.get("available", {})
        running = 0
        for states in (tasks.get("data") or {}).values() \
                if isinstance(tasks.get("data"), dict) else []:
            if isinstance(states, dict):
                running += states.get("RUNNING", 0)
        st = ostats.get("stats") or {}
        return {
            "ts": time.time(),
            "cpu_used": total.get("CPU", 0.0) - avail.get("CPU", 0.0),
            "cpu_total": total.get("CPU", 0.0),
            "tpu_used": total.get("TPU", 0.0) - avail.get("TPU", 0.0),
            "tasks_running": running,
            "store_used_mb": round(st.get("used_bytes", 0) / 1e6, 2),
            "store_spilled": st.get("num_spilled", 0),
        }

    def profile(self, pid: int, duration: float = 2.0) -> dict:
        """Sampling profile of a live worker (reference: dashboard
        profile_manager.py) — folded stacks via the node's router."""
        try:
            (reply,) = self._request_many(
                [{"t": "profile_worker", "pid": pid,
                  "duration": duration}], timeout=duration + 40)
        except RuntimeError as e:
            return {"pid": pid, "error": str(e)}
        return {"pid": pid, "folded": reply.get("folded", "")}


class Dashboard:
    def __init__(self, address: str, host: str = "127.0.0.1",
                 port: int = 8265, history_interval_s: float = 5.0,
                 history_points: int = 720):
        from collections import deque
        source = _StateSource(address)
        self._source_address = address
        self._history: "deque[dict]" = deque(maxlen=history_points)
        self._history_interval = history_interval_s
        self._history_stop = threading.Event()
        history = self._history

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                from urllib.parse import parse_qs, urlparse
                parsed = urlparse(self.path)
                path = parsed.path.rstrip("/") or "/"
                qs = parse_qs(parsed.query)
                try:
                    if path == "/":
                        self._send(200, _PAGE.encode(),
                                   "text/html; charset=utf-8")
                    elif path == "/api/summary":
                        self._send(200,
                                   json.dumps(source.summary(),
                                              default=str).encode(),
                                   "application/json")
                    elif path.startswith("/api/tasks/"):
                        tid = path.rsplit("/", 1)[1]
                        self._send(200, json.dumps(
                            source.task_events(tid),
                            default=str).encode(), "application/json")
                    elif path == "/api/logs":
                        name = (qs.get("name") or [None])[0]
                        self._send(200, json.dumps(
                            source.worker_logs(name),
                            default=str).encode(), "application/json")
                    elif path == "/api/stack":
                        pid = int((qs.get("pid") or ["0"])[0])
                        self._send(200, json.dumps(
                            source.stack_dump(pid),
                            default=str).encode(), "application/json")
                    elif path == "/api/metrics/history":
                        self._send(200, json.dumps(
                            list(history), default=str).encode(),
                            "application/json")
                    elif path == "/api/profile":
                        pid = int((qs.get("pid") or ["0"])[0])
                        dur = float((qs.get("duration") or ["2"])[0])
                        self._send(200, json.dumps(
                            source.profile(pid, dur),
                            default=str).encode(), "application/json")
                    elif path == "/api/flame":
                        from ray_tpu.util.profiling import flamegraph_svg
                        pid = int((qs.get("pid") or ["0"])[0])
                        dur = float((qs.get("duration") or ["2"])[0])
                        prof = source.profile(pid, dur)
                        if prof.get("error"):
                            self._send(502, json.dumps(prof).encode(),
                                       "application/json")
                        else:
                            svg = flamegraph_svg(prof["folded"])
                            self._send(200, svg.encode(),
                                       "image/svg+xml")
                    else:
                        self._send(404, b'{"error": "not found"}',
                                   "application/json")
                except Exception as e:
                    self._send(502, json.dumps(
                        {"error": str(e)}).encode(), "application/json")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="raytpu-dashboard")
        self._thread.start()

        def sample_loop():
            src = _StateSource(self._source_address)
            while not self._history_stop.wait(self._history_interval):
                try:
                    self._history.append(src.metrics_sample())
                except Exception:
                    pass   # cluster briefly unreachable: skip the point
        self._sampler = threading.Thread(target=sample_loop, daemon=True,
                                         name="raytpu-dash-metrics")
        self._sampler.start()

    def stop(self) -> None:
        self._history_stop.set()
        self._server.shutdown()
        self._server.server_close()
