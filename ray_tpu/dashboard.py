"""Web dashboard: cluster state over HTTP.

Reference capability: the Ray dashboard (reference: dashboard/ — node /
actor / job / object views over the state APIs).  Dependency-free shape:
one ThreadingHTTPServer serving a static single-page UI plus JSON
endpoints backed by observer connections to a node service (the same
read-only protocol the CLI uses), so it can point at ANY live cluster.

Run: ``python -m ray_tpu dashboard --address <node> [--port 8265]``.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title><style>
body { font-family: system-ui, sans-serif; margin: 1.5rem; color: #222; }
h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.4rem; }
table { border-collapse: collapse; min-width: 40rem; }
th, td { text-align: left; padding: .25rem .7rem; border-bottom:
  1px solid #ddd; font-size: .85rem; }
th { background: #f5f5f5; }
.ok { color: #0a7d36; } .bad { color: #c0392b; }
#updated { color: #888; font-size: .8rem; }
</style></head><body>
<h1>ray_tpu dashboard</h1><div id="updated"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Resources</h2><table id="resources"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Task summary</h2><table id="tasks"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Object store</h2><table id="objects"></table>
<script>
function esc(v) {
  // cluster-supplied strings (names, entrypoints) are untrusted —
  // escape everything; trusted markup opts in via {html: "..."}
  if (v && typeof v === "object" && "html" in v) return v.html;
  return String(v).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/>/g, "&gt;").replace(/"/g, "&quot;");
}
function row(cells, tag) {
  return "<tr>" + cells.map(c => `<${tag||"td"}>${esc(c)}</${tag||"td"}>`)
    .join("") + "</tr>";
}
function fill(id, header, rows) {
  document.getElementById(id).innerHTML =
    row(header, "th") + rows.map(r => row(r)).join("");
}
async function refresh() {
  try {
    const s = await (await fetch("/api/summary")).json();
    fill("nodes", ["node", "address", "alive", "total", "available",
                   "queued"],
      s.nodes.map(n => [n.node_id.slice(0, 12), n.address,
        n.alive ? {html: '<span class="ok">alive</span>'}
                : {html: '<span class="bad">dead</span>'},
        JSON.stringify(n.resources), JSON.stringify(n.available),
        JSON.stringify(n.queued || {})]));
    fill("resources", ["resource", "available", "total"],
      Object.keys(s.resources.total).map(k =>
        [k, s.resources.available[k] ?? 0, s.resources.total[k]]));
    fill("actors", ["actor", "class", "name", "state"],
      s.actors.map(a => [a.actor_id.slice(0, 12), a.class_name,
                         a.name || "-", a.state]));
    fill("tasks", ["function", "states"],
      Object.entries(s.tasks.cluster).map(([k, v]) =>
        [k, JSON.stringify(v)]));
    fill("jobs", ["job", "status", "entrypoint"],
      s.jobs.map(j => [j.job_id, j.status, j.entrypoint]));
    fill("objects", ["metric", "value"],
      Object.entries(s.object_store).map(([k, v]) => [k, v]));
    document.getElementById("updated").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    document.getElementById("updated").textContent = "refresh failed: " + e;
  }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class _StateSource:
    """Observer-protocol reads against a node service (one short-lived
    connection per snapshot — read-only, no runtime needed; shared wire
    implementation with the CLI, error replies raise)."""

    def __init__(self, address: str):
        self.address = address

    def _request_many(self, queries: list[dict]) -> list[dict]:
        from ray_tpu.core.observer import observer_query
        return observer_query(self.address, queries)

    def summary(self) -> dict:
        from ray_tpu.util.state import group_counts
        replies = self._request_many([
            {"t": "state", "what": "nodes"},
            {"t": "state", "what": "resources"},
            {"t": "state", "what": "cluster_actors"},
            {"t": "state", "what": "actors"},
            {"t": "state", "what": "tasks"},
            {"t": "object_stats"},
            {"t": "kv_keys", "prefix": b"job:"},
        ])
        nodes, res, cactors, lactors, tasks, ostats, jkeys = replies
        actors = cactors["data"] or lactors["data"]
        jobs = []
        job_keys = [k for k in jkeys.get("keys", [])
                    if not k.endswith(b":logs")]
        if job_keys:
            job_replies = self._request_many(
                [{"t": "kv_get", "key": k} for k in job_keys])
            for r in job_replies:
                if r.get("value"):
                    try:
                        jobs.append(json.loads(r["value"]))
                    except Exception:
                        pass
        return {
            "nodes": nodes["data"],
            "resources": res["data"],
            "actors": actors,
            "tasks": group_counts(tasks["data"], "name"),
            "object_store": ostats["stats"],
            "jobs": jobs,
            "time": time.time(),
        }


class Dashboard:
    def __init__(self, address: str, host: str = "127.0.0.1",
                 port: int = 8265):
        source = _StateSource(address)

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].rstrip("/") or "/"
                try:
                    if path == "/":
                        self._send(200, _PAGE.encode(),
                                   "text/html; charset=utf-8")
                    elif path == "/api/summary":
                        self._send(200,
                                   json.dumps(source.summary(),
                                              default=str).encode(),
                                   "application/json")
                    else:
                        self._send(404, b'{"error": "not found"}',
                                   "application/json")
                except Exception as e:
                    self._send(502, json.dumps(
                        {"error": str(e)}).encode(), "application/json")

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="raytpu-dashboard")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
