"""Multi-node-on-one-machine test cluster.

The analogue of the reference's ``ray.cluster_utils.Cluster``
(reference: python/ray/cluster_utils.py:102 — start a head plus N
simulated nodes in one process for integration tests; the reference's
virtual-cluster conftest fixture is python/ray/tests/conftest.py:375).

Each node is a real ``NodeService`` with its own listener, shm arena
(distinct session string), worker subprocess pool, and head channel —
only the event loops share this process.  ``kill_node`` severs a node the
hard way (stops its loop and kills its workers) to exercise head-side
death detection and recovery.
"""

from __future__ import annotations

import os
import shutil
import time
import uuid
from typing import Optional

from ray_tpu._config import RayTpuConfig
from ray_tpu.core.head import HeadService
from ray_tpu.core.node import NodeService


class Cluster:
    def __init__(self, config: Optional[RayTpuConfig] = None,
                 head_persistence: bool = False):
        self.config = config or RayTpuConfig()
        self.session = uuid.uuid4().hex
        self.base_dir = os.path.join("/tmp/ray_tpu",
                                     f"cluster_{self.session[:8]}")
        os.makedirs(self.base_dir, exist_ok=True)
        self.persistence_path = (os.path.join(self.base_dir, "head.state")
                                 if head_persistence else None)
        self.head = HeadService(self.config, self.session,
                                persistence_path=self.persistence_path)
        self.head.start_thread()
        self.nodes: list[NodeService] = []

    def restart_head(self, simulate_machine_loss: bool = False) -> None:
        """Kill the head and bring a new one up on the SAME address with
        the persisted state; nodes rejoin automatically (head-FT test
        shape — reference: GCS restart with Redis-backed storage).

        ``simulate_machine_loss`` deletes the local snapshot first and
        recovers from a surviving node's replica instead — the
        lose-the-head-MACHINE story the reference needs Redis for."""
        assert self.persistence_path, "construct with head_persistence=True"
        port = int(self.head.address.rsplit(":", 1)[1])
        self.head.stop()
        recover_from = None
        if simulate_machine_loss:
            try:
                os.remove(self.persistence_path)
            except OSError:
                pass
            alive = [n for n in self.nodes
                     if n._thread is not None and n._thread.is_alive()]
            assert alive, "machine-loss recovery needs a surviving node"
            # every survivor is offered: recovery picks the freshest
            # replica by seq (a fan-out may have missed some nodes)
            recover_from = ",".join(n.address for n in alive)
        deadline = time.time() + 30
        last_err = None
        while time.time() < deadline:
            try:
                self.head = HeadService(
                    self.config, self.session, port=port,
                    persistence_path=self.persistence_path,
                    recover_from=recover_from)
                break
            except OSError as e:   # port still in TIME_WAIT
                last_err = e
                time.sleep(0.2)
        else:
            raise RuntimeError(f"could not rebind head port: {last_err}")
        self.head.start_thread()

    @property
    def head_address(self) -> str:
        return self.head.address

    def add_node(self, num_cpus: float = 1, num_tpus: float = 0,
                 resources: Optional[dict] = None,
                 object_store_memory: Optional[int] = None) -> NodeService:
        idx = len(self.nodes)
        # NOTE: the shm arena name is derived from session[:8]
        # (object_store.arena_name), so the node discriminator must land
        # inside the first 8 chars or every node shares one arena
        session = f"{self.session[:5]}n{idx:02d}{self.session[5:12]}"
        session_dir = os.path.join(self.base_dir, f"node{idx}")
        cfg = self.config
        if object_store_memory is not None:
            d = cfg.to_dict()
            d["object_store_memory"] = object_store_memory
            cfg = RayTpuConfig(d)
        node = NodeService(cfg, session, session_dir,
                           num_cpus=num_cpus, num_tpus=num_tpus,
                           resources=resources,
                           head_address=self.head.address,
                           stop_on_driver_exit=False)
        node.start_thread()
        self.nodes.append(node)
        return node

    def wait_for_nodes(self, timeout: float = 10.0) -> None:
        """Block until the head sees every node AND every node's own
        cluster view has converged (so spillover decisions are sound the
        moment a test starts submitting)."""
        deadline = time.time() + timeout
        want = len(self.nodes)
        alive = 0
        while time.time() < deadline:
            alive = sum(1 for n in self.head.nodes.values() if n.alive)
            if alive >= want and all(
                    len(n.cluster_view) >= want for n in self.nodes):
                return
            time.sleep(0.05)
        raise TimeoutError(f"only {alive}/{want} nodes registered / synced")

    def kill_node(self, node: NodeService) -> None:
        """Hard-stop a node (loop + workers) so the head detects death."""
        node.stop()

    def drain_node(self, node: NodeService,
                   deadline_s: float = 30.0) -> None:
        """Gracefully decommission a node: the head flips it to
        DRAINING (no new placements) and pushes node_drain; the node
        re-parks its queue, finishes running work under the deadline,
        hands owned objects to a survivor, and exits via drain_done."""
        self.head.request_drain(node.node_id.hex(), deadline_s)

    def wait_node_gone(self, node: NodeService,
                       timeout: float = 60.0) -> None:
        """Block until the head no longer counts ``node`` alive (drain
        complete or death detected)."""
        deadline = time.time() + timeout
        h = node.node_id.hex()
        while time.time() < deadline:
            rec = self.head.nodes.get(h)
            if rec is not None and not rec.alive:
                return
            time.sleep(0.05)
        raise TimeoutError(f"node {h[:12]} still alive after {timeout}s")

    def shutdown(self) -> None:
        for n in self.nodes:
            try:
                n.stop()
            except Exception:
                pass
        try:
            self.head.stop()
        except Exception:
            pass
        shutil.rmtree(self.base_dir, ignore_errors=True)
