"""Prometheus-format metrics export.

The analogue of the reference's metrics pipeline (reference:
python/ray/_private/metrics_agent.py:375 + src/ray/stats/metric_defs.cc)
scoped to a single dependency-free exporter: the node service registers a
snapshot callable, and a tiny HTTP thread serves it at ``/metrics`` in
the Prometheus text exposition format.  Enable with the
``metrics_export_port`` config flag (0 = disabled, the default).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


def _escape_label(v) -> str:
    """Prometheus text-exposition label escaping."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_le(bound: float) -> str:
    return "+Inf" if bound == float("inf") else repr(bound)


def render_prometheus(metrics: list[tuple]) -> str:
    """metrics: [(name, kind, help, value_or_labeled_values)] where the
    last element is a float OR a dict {labels_dict_as_tuple: float}.
    kind "histogram" takes {labels_tuple: {"buckets": [(le, cum), ...],
    "sum": s, "count": n}} (cumulative buckets ending at +Inf — the
    shape FlightRecorder.Histogram.snapshot produces) and renders the
    full ``_bucket``/``_sum``/``_count`` exposition."""
    lines: list[str] = []
    for name, kind, help_text, value in metrics:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        if kind == "histogram":
            for labels, h in sorted(value.items()):
                base = ",".join(f'{k}="{_escape_label(val)}"'
                                for k, val in labels)
                sep = "," if base else ""
                for bound, cum in h["buckets"]:
                    lines.append(f'{name}_bucket{{{base}{sep}'
                                 f'le="{_fmt_le(bound)}"}} {int(cum)}')
                lines.append(f"{name}_sum{{{base}}} {float(h['sum'])}")
                lines.append(f"{name}_count{{{base}}} {int(h['count'])}")
        elif isinstance(value, dict):
            for labels, v in sorted(value.items()):
                lab = ",".join(f'{k}="{_escape_label(val)}"'
                               for k, val in labels)
                lines.append(f"{name}{{{lab}}} {float(v)}")
        else:
            lines.append(f"{name} {float(value)}")
    return "\n".join(lines) + "\n"


def node_metrics_snapshot(svc) -> list[tuple]:
    """Gauge/counter snapshot of a NodeService.  Runs on the HTTP thread
    while the event loop mutates the tables, so every iteration retries
    over a list() copy (exactness is not required for monitoring)."""
    for attempt in range(4):
        try:
            return _snapshot_once(svc)
        except RuntimeError:   # dict changed size during iteration
            if attempt == 3:
                raise


def _snapshot_once(svc) -> list[tuple]:
    tasks_by_state: dict[tuple, int] = {}
    for tr in list(svc.tasks.values()):
        key = (("state", tr.state),)
        tasks_by_state[key] = tasks_by_state.get(key, 0) + 1
    actors_by_state: dict[tuple, int] = {}
    for ar in list(svc.actors.values()):
        key = (("state", ar.state),)
        actors_by_state[key] = actors_by_state.get(key, 0) + 1
    resources: dict[tuple, float] = {}
    for k, v in list(svc.total_resources.items()):
        resources[(("kind", "total"), ("resource", k))] = v
    for k, v in list(svc.available.items()):
        resources[(("kind", "available"), ("resource", k))] = v
    store = svc.store.stats()
    workers = sum(1 for c in list(svc.clients.values())
                  if c.kind in ("worker", "tpu_executor"))
    # per-queue depths + event-loop lag: the tick-loop health gauges
    # ("is the scheduler keeping up") that a task-count gauge can't show
    queue_depth = {
        (("queue", "runnable_cpu"),): float(len(svc.runnable_cpu)),
        (("queue", "runnable_tpu"),): float(len(svc.runnable_tpu)),
        (("queue", "runnable_zero"),): float(len(svc.runnable_zero)),
        (("queue", "dep_waiting"),): float(sum(
            len(v) for v in list(svc.dep_waiting.values()))),
        (("queue", "posted"),): float(len(svc._posted)),
    }
    out = [
        ("ray_tpu_tasks", "gauge", "Tasks by state on this node",
         tasks_by_state or {(("state", "none"),): 0}),
        ("ray_tpu_actors", "gauge", "Actors by state on this node",
         actors_by_state or {(("state", "none"),): 0}),
        ("ray_tpu_resources", "gauge", "Node resources",
         resources),
        ("ray_tpu_objects", "gauge", "Objects in the node table",
         float(len(svc.objects))),
        ("ray_tpu_object_store_used_bytes", "gauge",
         "Shared-memory store usage", float(store["used_bytes"])),
        ("ray_tpu_object_store_capacity_bytes", "gauge",
         "Shared-memory store capacity", float(store["capacity_bytes"])),
        ("ray_tpu_objects_spilled_total", "counter",
         "Objects spilled to disk", float(store["num_spilled"])),
        ("ray_tpu_objects_restored_total", "counter",
         "Objects restored from disk", float(store["num_restored"])),
        ("ray_tpu_workers", "gauge", "Connected worker processes",
         float(workers)),
        ("ray_tpu_runnable_tasks", "gauge", "Queued runnable tasks",
         float(len(svc.runnable_cpu) + len(svc.runnable_tpu)
               + len(svc.runnable_zero))),
        ("ray_tpu_queue_depth", "gauge",
         "Control-plane queue depths on this node", queue_depth),
        ("ray_tpu_event_loop_lag_seconds", "gauge",
         "How late the node event loop's last periodic tick ran",
         float(getattr(svc, "loop_lag_s", 0.0))),
    ]
    from ray_tpu.core import flight_recorder as _fr
    rec = _fr.active()
    if rec is not None:
        out.append((
            "ray_tpu_task_stage_duration_seconds", "histogram",
            "Per-stage task lifecycle latency (flight recorder; stage = "
            "interval ending at that stamp)", rec.metrics_snapshot()))
    return out


class MetricsExporter:
    """Serve /metrics over HTTP from a snapshot callable."""

    def __init__(self, snapshot: Callable[[], list], port: int = 0,
                 host: str = "127.0.0.1"):
        self._snapshot = snapshot
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = render_prometheus(exporter._snapshot()).encode()
                except Exception as e:   # snapshot raced a shutdown
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="raytpu-metrics")
        self._thread.start()

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
