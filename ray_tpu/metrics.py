"""Prometheus-format metrics export.

The analogue of the reference's metrics pipeline (reference:
python/ray/_private/metrics_agent.py:375 + src/ray/stats/metric_defs.cc)
scoped to a single dependency-free exporter: the node service registers a
snapshot callable, and a tiny HTTP thread serves it at ``/metrics`` in
the Prometheus text exposition format.  Enable with the
``metrics_export_port`` config flag (0 = disabled, the default).
"""

from __future__ import annotations

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


def _escape_label(v) -> str:
    """Prometheus text-exposition label escaping."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def render_prometheus(metrics: list[tuple]) -> str:
    """metrics: [(name, kind, help, value_or_labeled_values)] where the
    last element is a float OR a dict {labels_dict_as_tuple: float}."""
    lines: list[str] = []
    for name, kind, help_text, value in metrics:
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {kind}")
        if isinstance(value, dict):
            for labels, v in sorted(value.items()):
                lab = ",".join(f'{k}="{_escape_label(val)}"'
                               for k, val in labels)
                lines.append(f"{name}{{{lab}}} {float(v)}")
        else:
            lines.append(f"{name} {float(value)}")
    return "\n".join(lines) + "\n"


def node_metrics_snapshot(svc) -> list[tuple]:
    """Gauge/counter snapshot of a NodeService.  Runs on the HTTP thread
    while the event loop mutates the tables, so every iteration retries
    over a list() copy (exactness is not required for monitoring)."""
    for attempt in range(4):
        try:
            return _snapshot_once(svc)
        except RuntimeError:   # dict changed size during iteration
            if attempt == 3:
                raise


def _snapshot_once(svc) -> list[tuple]:
    tasks_by_state: dict[tuple, int] = {}
    for tr in list(svc.tasks.values()):
        key = (("state", tr.state),)
        tasks_by_state[key] = tasks_by_state.get(key, 0) + 1
    actors_by_state: dict[tuple, int] = {}
    for ar in list(svc.actors.values()):
        key = (("state", ar.state),)
        actors_by_state[key] = actors_by_state.get(key, 0) + 1
    resources: dict[tuple, float] = {}
    for k, v in list(svc.total_resources.items()):
        resources[(("kind", "total"), ("resource", k))] = v
    for k, v in list(svc.available.items()):
        resources[(("kind", "available"), ("resource", k))] = v
    store = svc.store.stats()
    workers = sum(1 for c in list(svc.clients.values())
                  if c.kind in ("worker", "tpu_executor"))
    return [
        ("ray_tpu_tasks", "gauge", "Tasks by state on this node",
         tasks_by_state or {(("state", "none"),): 0}),
        ("ray_tpu_actors", "gauge", "Actors by state on this node",
         actors_by_state or {(("state", "none"),): 0}),
        ("ray_tpu_resources", "gauge", "Node resources",
         resources),
        ("ray_tpu_objects", "gauge", "Objects in the node table",
         float(len(svc.objects))),
        ("ray_tpu_object_store_used_bytes", "gauge",
         "Shared-memory store usage", float(store["used_bytes"])),
        ("ray_tpu_object_store_capacity_bytes", "gauge",
         "Shared-memory store capacity", float(store["capacity_bytes"])),
        ("ray_tpu_objects_spilled_total", "counter",
         "Objects spilled to disk", float(store["num_spilled"])),
        ("ray_tpu_objects_restored_total", "counter",
         "Objects restored from disk", float(store["num_restored"])),
        ("ray_tpu_workers", "gauge", "Connected worker processes",
         float(workers)),
        ("ray_tpu_runnable_tasks", "gauge", "Queued runnable tasks",
         float(len(svc.runnable_cpu) + len(svc.runnable_tpu)
               + len(svc.runnable_zero))),
    ]


class MetricsExporter:
    """Serve /metrics over HTTP from a snapshot callable."""

    def __init__(self, snapshot: Callable[[], list], port: int = 0,
                 host: str = "127.0.0.1"):
        self._snapshot = snapshot
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802
                if self.path.rstrip("/") not in ("", "/metrics"):
                    self.send_response(404)
                    self.end_headers()
                    return
                try:
                    body = render_prometheus(exporter._snapshot()).encode()
                except Exception as e:   # snapshot raced a shutdown
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())
                    return
                self.send_response(200)
                self.send_header("Content-Type",
                                 "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):   # quiet
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True,
                                        name="raytpu-metrics")
        self._thread.start()

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
