"""Model zoo: TPU-first reference models for train/tune/rllib/serve.

The reference framework ships no model library of its own (it trains
user-supplied torch/TF models — e.g. the ResNet/GPT configs in its AIR
benchmarks, doc/source/ray-air/benchmarks.rst); here the flagship models
are part of the framework so every layer above (train, tune, rllib,
serve, bench) exercises the same TPU-native compute path: pure-jax
pytree params with logical sharding axes, scan-over-layers, pallas
attention, bf16 matmuls on the MXU.
"""

from ray_tpu.models.bert import (BERT, BERTConfig)
from ray_tpu.models.gpt import (GPT, GPTConfig)
from ray_tpu.models.mlp import (MLP, MLPConfig)
from ray_tpu.models.resnet import (ResNet, ResNetConfig)
from ray_tpu.models.zoo import (ActorCritic, ModelConfig)

__all__ = ["BERT", "BERTConfig", "GPT", "GPTConfig", "MLP", "MLPConfig",
           "ResNet", "ResNetConfig", "ActorCritic", "ModelConfig"]
