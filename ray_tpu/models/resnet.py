"""ResNet: TPU-first residual CNN (north-star config #1, ResNet-18/CIFAR).

Reference capability: the torch ResNet workloads in the reference's AIR
benchmarks (doc/source/ray-air/benchmarks.rst:166-174 — GPU image
training) and its train examples; the reference ships no model code of
its own.  TPU-first choices:

  * NHWC activations + HWIO kernels — the conv layout XLA:TPU tiles onto
    the MXU without transposes (channels on the lane dimension).
  * BatchNorm statistics are plain ``jnp.mean`` over the batch axis: under
    pjit with a dp-sharded batch the reduction is GLOBAL (XLA inserts the
    cross-replica psum), so distributed BN comes for free — no
    SyncBatchNorm machinery like torch DDP needs.
  * activations in ``cfg.dtype`` (bf16 by default on TPU), BN statistics
    and residual adds accumulate in f32.
  * functional (params, state) pairs — batch stats are explicit carry,
    so the train step stays a pure jittable function.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_DN = ("NHWC", "HWIO", "NHWC")


@dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 10
    stage_sizes: tuple = (2, 2, 2, 2)      # resnet-18
    num_filters: int = 64
    bottleneck: bool = False               # True for resnet-50/101/152
    cifar_stem: bool = True                # 3x3/s1 stem, no maxpool
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5

    @staticmethod
    def resnet18(**kw) -> "ResNetConfig":
        return ResNetConfig(**{**dict(stage_sizes=(2, 2, 2, 2)), **kw})

    @staticmethod
    def resnet34(**kw) -> "ResNetConfig":
        return ResNetConfig(**{**dict(stage_sizes=(3, 4, 6, 3)), **kw})

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(**{**dict(stage_sizes=(3, 4, 6, 3),
                                      bottleneck=True), **kw})

    @staticmethod
    def tiny(**kw) -> "ResNetConfig":
        """Test-sized config."""
        return ResNetConfig(**{**dict(stage_sizes=(1, 1), num_filters=8,
                                      dtype=jnp.float32), **kw})


# -- init ------------------------------------------------------------------

def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = np.sqrt(2.0 / fan_in)
    return (jax.random.normal(key, (kh, kw, cin, cout)) * std).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_state(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def _block_channels(cfg: ResNetConfig, stage: int) -> tuple:
    width = cfg.num_filters * (2 ** stage)
    return (width, width * 4) if cfg.bottleneck else (width, width)


def init_params(cfg: ResNetConfig, rng: jax.Array):
    """Returns (params, state) — state holds BN running statistics."""
    keys = iter(jax.random.split(rng, 256))
    pd = cfg.param_dtype
    stem_cin = 3
    if cfg.cifar_stem:
        stem = _conv_init(next(keys), 3, 3, stem_cin, cfg.num_filters, pd)
    else:
        stem = _conv_init(next(keys), 7, 7, stem_cin, cfg.num_filters, pd)
    params = {"stem_conv": stem, "stem_bn": _bn_init(cfg.num_filters, pd)}
    state = {"stem_bn": _bn_state(cfg.num_filters)}

    cin = cfg.num_filters
    for s, n_blocks in enumerate(cfg.stage_sizes):
        width, cout = _block_channels(cfg, s)
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            blk, bst = {}, {}
            if cfg.bottleneck:
                shapes = [(1, 1, cin, width), (3, 3, width, width),
                          (1, 1, width, cout)]
            else:
                shapes = [(3, 3, cin, width), (3, 3, width, cout)]
            for i, (kh, kw, ci, co) in enumerate(shapes):
                blk[f"conv{i}"] = _conv_init(next(keys), kh, kw, ci, co, pd)
                blk[f"bn{i}"] = _bn_init(co, pd)
                bst[f"bn{i}"] = _bn_state(co)
            if cin != cout or (b == 0 and s > 0):
                blk["proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                blk["proj_bn"] = _bn_init(cout, pd)
                bst["proj_bn"] = _bn_state(cout)
            params[name] = blk
            state[name] = bst
            cin = cout

    params["head"] = {
        "w": (jax.random.normal(next(keys), (cin, cfg.num_classes))
              * 0.01).astype(pd),
        "b": jnp.zeros((cfg.num_classes,), pd)}
    return params, state


# -- forward ---------------------------------------------------------------

def _bn(x, p, st, cfg: ResNetConfig, train: bool):
    """BatchNorm over (N, H, W).  Returns (y, new_stats)."""
    xf = x.astype(jnp.float32)
    if train:
        mean = jnp.mean(xf, axis=(0, 1, 2))
        var = jnp.var(xf, axis=(0, 1, 2))
        m = cfg.bn_momentum
        new = {"mean": m * st["mean"] + (1 - m) * mean,
               "var": m * st["var"] + (1 - m) * var}
    else:
        mean, var = st["mean"], st["var"]
        new = st
    y = (xf - mean) * lax.rsqrt(var + cfg.bn_eps)
    y = y * p["scale"].astype(jnp.float32) + p["bias"].astype(jnp.float32)
    return y.astype(x.dtype), new


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=_DN)


def forward(params, state, x, cfg: ResNetConfig, *, train: bool = True):
    """x [N, H, W, 3] → (logits [N, classes] f32, new_state)."""
    x = x.astype(cfg.dtype)
    new_state = {}
    stride0 = 1 if cfg.cifar_stem else 2
    x = _conv(x, params["stem_conv"], stride0)
    x, new_state["stem_bn"] = _bn(x, params["stem_bn"], state["stem_bn"],
                                  cfg, train)
    x = jax.nn.relu(x)
    if not cfg.cifar_stem:
        x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")

    for s, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            name = f"stage{s}_block{b}"
            blk, bst = params[name], state[name]
            nst = {}
            stride = 2 if (b == 0 and s > 0) else 1
            resid = x
            y = x
            n_convs = 3 if cfg.bottleneck else 2
            for i in range(n_convs):
                cs = stride if i == (1 if cfg.bottleneck else 0) else 1
                y = _conv(y, blk[f"conv{i}"], cs)
                y, nst[f"bn{i}"] = _bn(y, blk[f"bn{i}"], bst[f"bn{i}"],
                                       cfg, train)
                if i < n_convs - 1:
                    y = jax.nn.relu(y)
            if "proj" in blk:
                resid = _conv(resid, blk["proj"], stride)
                resid, nst["proj_bn"] = _bn(resid, blk["proj_bn"],
                                            bst["proj_bn"], cfg, train)
            x = jax.nn.relu(y + resid)
            new_state[name] = nst

    x = jnp.mean(x.astype(jnp.float32), axis=(1, 2))
    h = params["head"]
    logits = x @ h["w"].astype(jnp.float32) + h["b"].astype(jnp.float32)
    return logits, new_state


def loss_fn(params, state, batch, cfg: ResNetConfig, *, train: bool = True):
    """batch = {"x": [N,H,W,3], "y": [N] int labels} →
    (loss, (new_state, metrics))."""
    logits, new_state = forward(params, state, batch["x"], cfg, train=train)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, batch["y"][:, None], axis=-1)[:, 0]
    loss = jnp.mean(logz - gold)
    acc = jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))
    return loss, (new_state, {"accuracy": acc})


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


class ResNet:
    """OO convenience wrapper over the functional API."""

    def __init__(self, cfg: ResNetConfig):
        self.cfg = cfg

    def init(self, rng):
        return init_params(self.cfg, rng)

    def apply(self, params, state, x, **kw):
        return forward(params, state, x, self.cfg, **kw)

    def loss(self, params, state, batch, **kw):
        return loss_fn(params, state, batch, self.cfg, **kw)
