"""GPT: decoder-only transformer, TPU-first.

Flagship model of the framework (north-star config: GPT-2 124M DP×8, see
BASELINE.md).  Design choices are all MXU/HBM-driven:

  * params are a plain pytree with per-leaf *logical axes* — sharding is
    declarative (parallel.sharding rules map logical→mesh axes; pjit/XLA
    inserts the collectives).  dp/fsdp/tp/sp all come from the same
    forward function with different rules, no model rewrite.
  * layers are STACKED (leading ``layers`` dim) and the forward runs
    ``lax.scan`` over them: one compiled layer body regardless of depth,
    so compile time is O(1) in n_layers and XLA pipelines the weight
    loads.
  * attention dispatches to the pallas flash kernel on TPU, and to
    shard_map'd ring attention when the mesh has an ``sp`` axis (exact
    long-context attention, kv rotating over the ICI ring).
  * optional ``remat`` wraps the scanned body in jax.checkpoint —
    activation memory O(sqrt) trade per the HBM charter.
  * activations run in ``cfg.dtype`` (bf16 by default), params and the
    softmax/logsumexp accumulators in f32.

The reference has no analogue (it rides torch models); capability parity
target is the GPT-2 124M benchmark workload in
release/air_tests/air_benchmarks (SURVEY.md §6 north-star configs).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec

from ray_tpu.ops.attention import attention
from ray_tpu.ops.ring_attention import ring_attention
from ray_tpu.parallel.sharding import (DEFAULT_LLM_RULES, Rules, spec_for)

from ray_tpu.parallel.jax_compat import shard_map


@dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304          # gpt-2 vocab padded to a multiple of 128
    max_seq: int = 1024
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    dropout: float = 0.0             # framework trains with no dropout by default
    dtype: Any = jnp.bfloat16        # activation dtype (MXU-native)
    param_dtype: Any = jnp.float32
    remat: bool = True
    remat_policy: Optional[str] = None   # None=full recompute, "dots"
    tie_embeddings: bool = True
    attn_impl: Optional[str] = None  # None=auto, "flash", "reference"
    attn_block_q: int = 512          # pallas flash tile sizes (fwd + bwd)
    attn_block_k: int = 512
    pp_microbatches: Optional[int] = None  # None = 2*pp stages (GPipe)
    # MoE (0 = dense MLP).  When n_experts > 0 every layer's MLP becomes
    # a top-k routed expert layer (GShard/Switch formulation: static
    # capacity, one-hot dispatch/combine einsums — the dispatch einsum
    # IS the all-to-all when experts are sharded over the ep mesh axis).
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01     # load-balance aux loss coefficient

    def __post_init__(self):
        if self.remat_policy not in (None, "dots", "dots_flash"):
            raise ValueError(
                f"unknown remat_policy {self.remat_policy!r}; expected "
                "None (full recompute), 'dots', or 'dots_flash' (dots + "
                "saved flash-attention out/lse so the backward pass never "
                "re-runs the attention forward kernel)")
        if self.n_experts:
            if not 1 <= self.expert_top_k <= self.n_experts:
                raise ValueError(
                    f"expert_top_k {self.expert_top_k} must be in "
                    f"[1, n_experts={self.n_experts}]")
            if self.capacity_factor <= 0:
                raise ValueError(
                    f"capacity_factor {self.capacity_factor} must be > 0")

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def gpt2_124m(**kw) -> "GPTConfig":
        return GPTConfig(**{**dict(d_model=768, n_heads=12, n_layers=12,
                                   d_ff=3072, max_seq=1024), **kw})

    @staticmethod
    def tiny(**kw) -> "GPTConfig":
        """Test-sized config (CPU-mesh friendly)."""
        return GPTConfig(**{**dict(vocab_size=512, max_seq=128, d_model=64,
                                   n_heads=4, n_layers=2, d_ff=128,
                                   remat=False), **kw})

    @staticmethod
    def tiny_moe(**kw) -> "GPTConfig":
        """Test-sized mixture-of-experts config."""
        return GPTConfig.tiny(**{**dict(n_experts=4, expert_top_k=2,
                                        dtype=jnp.float32), **kw})


# -- params ----------------------------------------------------------------

# logical axes per leaf; "layers" is the scan dim, sharded over pp when
# the mesh has one (DEFAULT_LLM_RULES maps layers->pp; pruned to None on
# meshes without a pp axis).
PARAM_AXES = {
    "wte": ("vocab", "embed"),
    "wpe": (None, "embed"),
    "ln_f_scale": ("embed",),
    "ln_f_bias": ("embed",),
    "layers": {
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "wqkv": ("layers", "embed", "qkv"),
        "wo": ("layers", "heads", "embed"),  # [L, d, d]: in-dim is head-major
        "bo": ("layers", "embed"),
        "ln2_scale": ("layers", "embed"),
        "ln2_bias": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "b_down": ("layers", "embed"),
    },
}


# MoE layers swap the dense MLP leaves for expert-stacked ones; the
# "expert" logical axis maps to the ep mesh axis (sharding.py rules)
MOE_MLP_AXES = {
    "w_router": ("layers", "embed", None),
    "w_up": ("layers", "expert", "embed", "mlp"),
    "b_up": ("layers", "expert", "mlp"),
    "w_down": ("layers", "expert", "mlp", "embed"),
    "b_down": ("layers", "expert", "embed"),
}


def param_logical_axes(cfg: GPTConfig):
    axes = dict(PARAM_AXES)
    if cfg.n_experts:
        axes["layers"] = {**axes["layers"], **MOE_MLP_AXES}
    if not cfg.tie_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


def init_params(cfg: GPTConfig, rng: jax.Array):
    """GPT-2 style init: N(0, 0.02), residual projections scaled by
    1/sqrt(2*n_layers)."""
    k = iter(jax.random.split(rng, 16))
    d, L, f = cfg.d_model, cfg.n_layers, cfg.d_ff
    std = 0.02
    res_std = std / math.sqrt(2 * L)
    pd = cfg.param_dtype

    def norm(key, shape, s=std):
        return (jax.random.normal(key, shape) * s).astype(pd)

    if cfg.n_experts:
        E = cfg.n_experts
        mlp = {
            "w_router": norm(next(k), (L, d, E)),
            "w_up": norm(next(k), (L, E, d, f)),
            "b_up": jnp.zeros((L, E, f), pd),
            "w_down": norm(next(k), (L, E, f, d), res_std),
            "b_down": jnp.zeros((L, E, d), pd),
        }
    else:
        mlp = {
            "w_up": norm(next(k), (L, d, f)),
            "b_up": jnp.zeros((L, f), pd),
            "w_down": norm(next(k), (L, f, d), res_std),
            "b_down": jnp.zeros((L, d), pd),
        }
    params = {
        "wte": norm(next(k), (cfg.vocab_size, d)),
        "wpe": norm(next(k), (cfg.max_seq, d), 0.01),
        "ln_f_scale": jnp.ones((d,), pd),
        "ln_f_bias": jnp.zeros((d,), pd),
        "layers": {
            "ln1_scale": jnp.ones((L, d), pd),
            "ln1_bias": jnp.zeros((L, d), pd),
            "wqkv": norm(next(k), (L, d, 3 * d)),
            "wo": norm(next(k), (L, d, d), res_std),
            "bo": jnp.zeros((L, d), pd),
            "ln2_scale": jnp.ones((L, d), pd),
            "ln2_bias": jnp.zeros((L, d), pd),
            **mlp,
        },
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(next(k), (d, cfg.vocab_size))
    return params


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


# -- forward ---------------------------------------------------------------

def _layer_norm(x, scale, bias, eps=1e-5):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _constrain(x, logical, mesh, rules):
    if mesh is None:
        return x
    spec = spec_for(logical, rules, mesh)
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))


def _attend(q, k, v, cfg: GPTConfig, mesh: Optional[Mesh], rules: Rules):
    """[b, h, s, hd] attention; ring attention when seq is sp-sharded."""
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        spec = spec_for(("batch", "heads", "seq", "kv"), rules, mesh)
        ring = partial(ring_attention, axis_name="sp", causal=True)
        return shard_map(ring, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
    if cfg.remat_policy == "dots_flash":
        # lse-exposing flash variant: the kernel outputs are named
        # (flash_out/flash_lse) inside its vjp, so the scan's checkpoint
        # policy saves them and the backward pass reconstructs the layer
        # without re-running the attention forward kernel
        from ray_tpu.ops.flash_attention import flash_attention_with_lse
        tile_ok = (q.shape[-2] % 128 == 0 and k.shape[-2] % 128 == 0
                   and q.shape[-1] in (64, 128, 256))
        on_tpu = jax.default_backend() == "tpu"
        if on_tpu and tile_ok and cfg.attn_impl in (None, "flash"):
            out, _lse = flash_attention_with_lse(
                q, k, v, causal=True,
                block_q=cfg.attn_block_q, block_k=cfg.attn_block_k)
            return out
    return attention(q, k, v, causal=True, impl=cfg.attn_impl,
                     block_q=cfg.attn_block_q, block_k=cfg.attn_block_k)


def _moe_mlp(y, lp, cfg: GPTConfig, mesh: Optional[Mesh], rules: Rules):
    """Top-k routed expert MLP, GShard/Switch formulation with groups.

    Tokens route in GROUPS (one group per sequence, the GShard device
    group): capacity is per group (C = cf·k·s/E), so the dispatch and
    combine tensors are [G, s, E, C] — O(s²) per group, with the group
    dim sharded over the data axes, NOT O(N²) global.  The dispatch
    einsum scatters tokens into each group's [E, C, d] buffer; with
    experts sharded over ``ep`` ("expert"→ep rule) that einsum IS the
    all-to-all, inserted by XLA, while expert compute stays sharded over
    the data axes on the group dim (green-field capability, SURVEY.md §7
    M4: the reference has no MoE engine).  Returns
    (output [b, s, d], load-balance aux loss scalar)."""
    b, s, d = y.shape                  # groups G = b, tokens/group n = s
    E, k = cfg.n_experts, cfg.expert_top_k
    C = max(1, int(math.ceil(cfg.capacity_factor * k * s / E)))

    logits = jnp.einsum("gnd,de->gne", y.astype(jnp.float32),
                        lp["w_router"].astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)              # [G, n, E] f32

    remaining = probs
    counts = jnp.zeros((b, E), jnp.float32)   # per-group expert fill
    combine = jnp.zeros((b, s, E, C), jnp.float32)
    gates_sum = jnp.zeros((b, s), jnp.float32)
    top1_frac = None
    for i in range(k):
        idx = jnp.argmax(remaining, axis=-1)              # [G, n]
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [G, n, E]
        gate = jnp.sum(remaining * mask, axis=-1)         # [G, n]
        # position of each token in its chosen expert's queue (0-based,
        # offset by earlier rounds' fill of this group's queues)
        pos = jnp.cumsum(mask, axis=1) - 1.0 + counts[:, None, :]
        posn = jnp.sum(pos * mask, axis=-1)               # [G, n]
        keep = (posn < C).astype(jnp.float32)             # capacity drop
        disp = (mask * keep[..., None])[..., None] \
            * jax.nn.one_hot(posn.astype(jnp.int32), C,
                             dtype=jnp.float32)[..., None, :]
        combine = combine + gate[..., None, None] * disp  # [G, n, E, C]
        gates_sum = gates_sum + gate * keep
        counts = counts + jnp.sum(mask * keep[..., None], axis=1)
        if i == 0:
            top1_frac = jnp.mean(mask, axis=(0, 1))       # [E]
        remaining = remaining * (1.0 - mask)
    # normalize the selected gates to sum to 1 per token (GShard)
    combine = combine / jnp.maximum(gates_sum, 1e-9)[..., None, None]
    dispatch = (combine > 0).astype(cfg.dtype)            # [G, n, E, C]

    # Switch load-balance loss: E * Σ_e f_e · P_e (f from the top-1
    # routing decision, P the mean router probability)
    aux = E * jnp.sum(top1_frac * jnp.mean(probs, axis=(0, 1)))

    yd = y.astype(cfg.dtype)
    expert_in = jnp.einsum("gnec,gnd->gecd", dispatch, yd)  # [G, E, C, d]
    expert_in = _constrain(expert_in, ("batch", "expert", None, "embed"),
                           mesh, rules)
    hid = jnp.einsum("gecd,edf->gecf", expert_in,
                     lp["w_up"].astype(cfg.dtype)) \
        + lp["b_up"].astype(cfg.dtype)[None, :, None, :]
    hid = _constrain(hid, ("batch", "expert", None, "mlp"), mesh, rules)
    hid = jax.nn.gelu(hid)
    out_e = jnp.einsum("gecf,efd->gecd", hid,
                       lp["w_down"].astype(cfg.dtype)) \
        + lp["b_down"].astype(cfg.dtype)[None, :, None, :]
    out_e = _constrain(out_e, ("batch", "expert", None, "embed"),
                       mesh, rules)
    out = jnp.einsum("gnec,gecd->gnd", combine.astype(cfg.dtype), out_e)
    return out, aux


def _transformer_layer(x, lp, cfg: GPTConfig, mesh: Optional[Mesh],
                       rules: Rules, return_kv: bool = False):
    """One pre-LN transformer block; x [b, s, d], lp = one layer's params
    (no leading layers dim).  Returns (x, moe aux loss — 0 when dense);
    with ``return_kv`` also the per-head K/V ([b, h, s, hd] each) so a
    prefill pass can seed an incremental-decode cache
    (ray_tpu.inference.decode)."""
    b, s, _ = x.shape
    h, hd = cfg.n_heads, cfg.head_dim

    y = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
    qkv = jnp.einsum("bsd,de->bse", y, lp["wqkv"].astype(cfg.dtype))
    qkv = _constrain(qkv, ("batch", "seq", "qkv"), mesh, rules)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):  # [b, s, d] -> [b, h, s, hd]
        return t.reshape(b, s, h, hd).transpose(0, 2, 1, 3)

    kh, vh = heads(k), heads(v)
    o = _attend(heads(q), kh, vh, cfg, mesh, rules)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, cfg.d_model)
    o = jnp.einsum("bsd,de->bse", o, lp["wo"].astype(cfg.dtype)) \
        + lp["bo"].astype(cfg.dtype)
    x = x + o
    x = _constrain(x, ("batch", "seq", "embed"), mesh, rules)

    y = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
    if cfg.n_experts:
        dn, aux = _moe_mlp(y, lp, cfg, mesh, rules)
    else:
        u = jnp.einsum("bsd,df->bsf", y, lp["w_up"].astype(cfg.dtype)) \
            + lp["b_up"].astype(cfg.dtype)
        u = _constrain(u, ("batch", "seq", "mlp"), mesh, rules)
        u = jax.nn.gelu(u)
        dn = jnp.einsum("bsf,fd->bsd", u, lp["w_down"].astype(cfg.dtype)) \
            + lp["b_down"].astype(cfg.dtype)
        aux = jnp.zeros((), jnp.float32)
    x = x + dn
    x = _constrain(x, ("batch", "seq", "embed"), mesh, rules)
    if return_kv:
        return x, aux, (kh, vh)
    return x, aux


def _layer_scan_body(cfg: GPTConfig, mesh, rules, return_kv: bool = False):
    """Scan body over a stacked layer dim, rematerialized per cfg.
    Carry is (x, accumulated moe aux loss); with ``return_kv`` each step
    also emits that layer's K/V heads (stacked to [L, b, h, s, hd] by the
    scan — the prefill cache layout)."""
    def layer(carry, lp):
        x, aux = carry
        if return_kv:
            x, a, kv = _transformer_layer(x, lp, cfg, mesh, rules,
                                          return_kv=True)
            return (x, aux + a), kv
        x, a = _transformer_layer(x, lp, cfg, mesh, rules)
        return (x, aux + a), None

    if cfg.remat:
        # "dots" keeps matmul outputs and recomputes only the cheap
        # elementwise/norm work in the backward pass — a fraction of
        # full-remat's extra FLOPs for modest activation memory
        # (the policy knob the scaling playbook recommends; validated
        # at GPTConfig construction).  "dots_flash" additionally saves
        # the named flash-attention outputs so the backward never
        # re-runs the attention forward kernel.
        cp = jax.checkpoint_policies
        if cfg.remat_policy == "dots":
            policy = cp.dots_with_no_batch_dims_saveable
        elif cfg.remat_policy == "dots_flash":
            policy = cp.save_from_both_policies(
                cp.dots_with_no_batch_dims_saveable,
                cp.save_only_these_names("flash_out", "flash_lse"))
        else:
            policy = None
        return jax.checkpoint(layer, policy=policy)
    return layer


def _embed(params, tokens, cfg: GPTConfig, mesh, rules):
    s = tokens.shape[1]
    x = params["wte"][tokens] + params["wpe"][:s][None, :, :]
    x = x.astype(cfg.dtype)
    return _constrain(x, ("batch", "seq", "embed"), mesh, rules)


def _head(params, x, cfg: GPTConfig, mesh, rules):
    x = _layer_norm(x, params["ln_f_scale"], params["ln_f_bias"])
    w_out = (params["wte"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("bsd,dv->bsv", x, w_out.astype(cfg.dtype))
    logits = _constrain(logits, ("batch", "seq", "vocab"), mesh, rules)
    return logits.astype(jnp.float32)


def forward(params, tokens, cfg: GPTConfig, *, mesh: Optional[Mesh] = None,
            rules: Rules = DEFAULT_LLM_RULES, return_aux: bool = False,
            return_kv: bool = False):
    """tokens [b, s] int32 → logits [b, s, vocab] (f32).

    With a mesh, activations carry sharding constraints so pjit lays out
    batch over dp/fsdp, heads/mlp over tp, seq over sp; without one it is
    an ordinary single-device jax function.  A mesh with pp > 1 runs the
    layer stack as a GPipe microbatch pipeline (parallel.pipeline).
    ``return_aux`` also returns the summed MoE load-balance loss.
    ``return_kv`` additionally returns the per-layer attention K/V
    ((k, v), each [L, b, h, s, hd]) — the prefill half of the
    incremental-decode path (ray_tpu.inference); the SAME forward math
    seeds the cache, so there is no separate prefill network to drift.
    """
    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        if return_kv:
            raise NotImplementedError(
                "return_kv (inference prefill) is not supported on a "
                "pp mesh; prefill with dp/tp sharding instead")
        return _forward_pipelined(params, tokens, cfg, mesh, rules,
                                  return_aux)

    x = _embed(params, tokens, cfg, mesh, rules)
    (x, aux), kv = lax.scan(_layer_scan_body(cfg, mesh, rules, return_kv),
                            (x, jnp.zeros((), jnp.float32)),
                            params["layers"])
    logits = _head(params, x, cfg, mesh, rules)
    if return_kv:
        return ((logits, aux, kv) if return_aux else (logits, kv))
    return (logits, aux) if return_aux else logits


def _forward_pipelined(params, tokens, cfg: GPTConfig, mesh: Mesh,
                       rules: Rules, return_aux: bool = False):
    """Pipeline-parallel forward: embedding and head run under GSPMD auto
    sharding (once, sharded over dp/tp); only the layer stack rides the
    pp pipeline (parallel.pipeline.pipeline_apply, single-hop ppermute
    hand-offs).  Composes with dp/fsdp/tp AND MoE (the load-balance aux
    loss rides the same ppermute hand-off as the activation, summed at
    the last stage); sp+pp is not supported (ring attention would nest
    shard_maps — shard long sequences with sp, deep stacks with pp)."""
    from ray_tpu.parallel.pipeline import pipeline_apply

    if mesh.shape.get("sp", 1) > 1:
        raise NotImplementedError(
            "sp and pp on the same mesh are not supported; shard long "
            "sequences with sp, deep stacks with pp")
    S = mesh.shape["pp"]
    if cfg.n_layers % S != 0:
        raise ValueError(f"n_layers {cfg.n_layers} not divisible by pp={S}")
    M = cfg.pp_microbatches or 2 * S
    b, s = tokens.shape
    if b % M != 0:
        raise ValueError(f"batch {b} not divisible by microbatches {M}")

    x = _embed(params, tokens, cfg, mesh, rules)
    x_mb = x.reshape(M, b // M, s, cfg.d_model)

    # dp/fsdp/tp are auto axes inside the pipeline's shard_map, so the
    # stage body keeps its usual logical-axis constraints (their specs
    # never mention pp)
    body = _layer_scan_body(cfg, mesh, rules)

    def stage_fn(local_layers, x, aux):
        (x, aux), _ = lax.scan(body, (x, aux), local_layers)
        return x, aux

    outs, aux = pipeline_apply(stage_fn, x_mb, params["layers"],
                               mesh=mesh, carry_aux=True)
    x = outs.reshape(b, s, cfg.d_model)
    logits = _head(params, x, cfg, mesh, rules)
    if return_aux:
        # per-microbatch means summed over M microbatches -> batch mean
        return logits, aux / M
    return logits


def loss_fn(params, batch, cfg: GPTConfig, *, mesh: Optional[Mesh] = None,
            rules: Rules = DEFAULT_LLM_RULES):
    """Next-token cross-entropy.  batch = {"tokens": [b, s+1] int32} or
    {"tokens": [b, s], "targets": [b, s]}."""
    tokens = batch["tokens"]
    if "targets" in batch:
        inp, tgt = tokens, batch["targets"]
    else:
        inp, tgt = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward(params, inp, cfg, mesh=mesh, rules=rules,
                          return_aux=True)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    ce = jnp.mean(logz - gold)
    if cfg.n_experts:
        return ce + cfg.moe_aux_weight * aux
    return ce


def sample_token(logits, *, temperature: float = 1.0,
                 rng: Optional[jax.Array] = None) -> jax.Array:
    """Next-token sampling head shared by ``generate()`` (the
    full-recompute correctness oracle) and the KV-cache engine
    (ray_tpu.inference.engine) — one implementation so greedy decode is
    token-identical across the two paths by construction.

    logits [..., vocab] f32 → token ids [...] int32.  temperature == 0.0
    is exact argmax (ties break to the lowest index); otherwise softmax
    sampling at the given temperature (``rng`` required).
    """
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if rng is None:
        raise ValueError("temperature > 0 sampling requires an rng key")
    return jax.random.categorical(
        rng, logits.astype(jnp.float32) / temperature, axis=-1
    ).astype(jnp.int32)


def generate(params, cfg: GPTConfig, prompt, max_new: int, *,
             rng: Optional[jax.Array] = None, temperature: float = 1.0):
    """Greedy/sampled decode via lax.scan (static shapes — the whole loop
    is one compiled program).  prompt [b, s0] int32, returns [b, s0+max_new].
    Simple full-recompute decode (no kv cache — every step re-runs the
    whole prefix).  The production incremental path lives in
    ray_tpu.inference (prefill seeds a KV cache via ``forward(...,
    return_kv=True)``, per-step decode reuses it); this path is kept as
    the correctness oracle the engine's greedy output is asserted
    token-identical against, and both share ``sample_token``."""
    b, s0 = prompt.shape
    total = s0 + max_new
    if total > cfg.max_seq:
        raise ValueError(f"{total} exceeds max_seq {cfg.max_seq}")
    toks = jnp.zeros((b, total), jnp.int32).at[:, :s0].set(prompt)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def step(carry, i):
        toks, rng = carry
        logits = forward(params, toks, cfg)[:, i - 1, :]
        if temperature == 0.0:
            nxt = sample_token(logits, temperature=0.0)
        else:
            rng, sub = jax.random.split(rng)
            nxt = sample_token(logits, temperature=temperature, rng=sub)
        toks = toks.at[:, i].set(nxt)
        return (toks, rng), None

    (toks, _), _ = lax.scan(step, (toks, rng), jnp.arange(s0, total))
    return toks


class GPT:
    """OO convenience wrapper over the functional API."""

    def __init__(self, cfg: GPTConfig):
        self.cfg = cfg

    def init(self, rng):
        return init_params(self.cfg, rng)

    def logical_axes(self):
        return param_logical_axes(self.cfg)

    def apply(self, params, tokens, **kw):
        return forward(params, tokens, self.cfg, **kw)

    def loss(self, params, batch, **kw):
        return loss_fn(params, batch, self.cfg, **kw)
