"""MLP classifier: the minimal model for tests and examples.

Analogue of the toy torch modules the reference's train/tune tests build
inline (e.g. python/ray/train/tests/test_torch_trainer.py); kept in the
zoo so examples/tests share one implementation.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class MLPConfig:
    in_dim: int = 784
    hidden: tuple = (128, 128)
    out_dim: int = 10
    dtype: Any = jnp.float32


def init_params(cfg: MLPConfig, rng: jax.Array):
    dims = (cfg.in_dim, *cfg.hidden, cfg.out_dim)
    keys = jax.random.split(rng, len(dims) - 1)
    return {
        f"layer{i}": {
            "w": (jax.random.normal(keys[i], (dims[i], dims[i + 1]))
                  * (2.0 / dims[i]) ** 0.5).astype(cfg.dtype),
            "b": jnp.zeros((dims[i + 1],), cfg.dtype),
        }
        for i in range(len(dims) - 1)
    }


def forward(params, x, cfg: MLPConfig):
    n = len(params)
    for i in range(n):
        lp = params[f"layer{i}"]
        x = x @ lp["w"] + lp["b"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def loss_fn(params, batch, cfg: MLPConfig):
    """batch = {"x": [b, in_dim], "y": [b] int labels}"""
    logits = forward(params, batch["x"], cfg)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, batch["y"][:, None], axis=-1)[:, 0]
    return jnp.mean(logz - gold)


def accuracy(params, batch, cfg: MLPConfig):
    logits = forward(params, batch["x"], cfg)
    return jnp.mean((jnp.argmax(logits, -1) == batch["y"]).astype(jnp.float32))


class MLP:
    def __init__(self, cfg: MLPConfig):
        self.cfg = cfg

    def init(self, rng):
        return init_params(self.cfg, rng)

    def apply(self, params, x):
        return forward(params, x, self.cfg)

    def loss(self, params, batch):
        return loss_fn(params, batch, self.cfg)
