"""RL model catalog: fcnet / visionnet / LSTM / GTrXL trunks + actor-critic
heads.

Reference capability: rllib/models/catalog.py (ModelCatalog) and the
torch nets under rllib/models/torch/{fcnet,visionnet,recurrent_net,
attention_net}.py (attention_net.py = GTrXL).  TPU redesign: every net is
a pure-jax (params pytree, forward fn) pair; recurrent state is an
explicit carry threaded with ``lax.scan`` so whole rollout windows
compile to one program, and the same trunk runs jitted on CPU rollout
workers and sharded on the TPU learner mesh.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.ops.attention import attention

Activation = Callable[[jax.Array], jax.Array]

_ACTS = {"tanh": jnp.tanh, "relu": jax.nn.relu, "gelu": jax.nn.gelu,
         "swish": jax.nn.swish}


def _dense_init(key, din, dout, scale=None, dtype=jnp.float32):
    std = np.sqrt(2.0 / din) if scale is None else scale
    return {"w": (jax.random.normal(key, (din, dout)) * std).astype(dtype),
            "b": jnp.zeros((dout,), dtype)}


def _dense(p, x):
    return x @ p["w"] + p["b"]


# -- FCNet (reference: rllib/models/torch/fcnet.py) ------------------------

@dataclass(frozen=True)
class FCNetConfig:
    in_dim: int
    hiddens: tuple = (256, 256)
    activation: str = "tanh"

    @property
    def out_dim(self) -> int:
        return self.hiddens[-1]


def fcnet_init(cfg: FCNetConfig, rng):
    dims = (cfg.in_dim, *cfg.hiddens)
    keys = jax.random.split(rng, len(dims) - 1)
    return {f"fc{i}": _dense_init(keys[i], dims[i], dims[i + 1])
            for i in range(len(dims) - 1)}


def fcnet_forward(params, x, cfg: FCNetConfig):
    act = _ACTS[cfg.activation]
    i = 0
    while f"fc{i}" in params:
        x = act(_dense(params[f"fc{i}"], x))
        i += 1
    return x


# -- VisionNet (reference: rllib/models/torch/visionnet.py) ----------------

@dataclass(frozen=True)
class VisionNetConfig:
    """Atari-style CNN trunk.  NHWC in/out (TPU conv layout)."""
    in_shape: tuple = (84, 84, 4)
    # (out_channels, kernel, stride) per conv layer
    conv_filters: tuple = ((16, 8, 4), (32, 4, 2))
    hidden: int = 256
    activation: str = "relu"

    @property
    def out_dim(self) -> int:
        return self.hidden


def visionnet_init(cfg: VisionNetConfig, rng):
    keys = iter(jax.random.split(rng, len(cfg.conv_filters) + 2))
    params = {}
    h, w, cin = cfg.in_shape
    for i, (cout, k, s) in enumerate(cfg.conv_filters):
        fan_in = k * k * cin
        params[f"conv{i}"] = (
            jax.random.normal(next(keys), (k, k, cin, cout))
            * np.sqrt(2.0 / fan_in)).astype(jnp.float32)
        h = -(-h // s)
        w = -(-w // s)
        cin = cout
    params["fc"] = _dense_init(next(keys), h * w * cin, cfg.hidden)
    return params


def visionnet_forward(params, x, cfg: VisionNetConfig):
    """x [B, H, W, C] (uint8 or float) → features [B, hidden]."""
    act = _ACTS[cfg.activation]
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32) / 255.0
    for i, (cout, k, s) in enumerate(cfg.conv_filters):
        x = lax.conv_general_dilated(
            x, params[f"conv{i}"].astype(x.dtype), (s, s), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = act(x)
    x = x.reshape(x.shape[0], -1)
    return act(_dense(params["fc"], x))


# -- LSTM (reference: rllib/models/torch/recurrent_net.py) -----------------

@dataclass(frozen=True)
class LSTMNetConfig:
    in_dim: int
    cell_size: int = 256

    @property
    def out_dim(self) -> int:
        return self.cell_size


def lstm_init(cfg: LSTMNetConfig, rng):
    k1, k2 = jax.random.split(rng)
    d, c = cfg.in_dim, cfg.cell_size
    return {"wx": _dense_init(k1, d, 4 * c, scale=np.sqrt(1.0 / d)),
            "wh": _dense_init(k2, c, 4 * c, scale=np.sqrt(1.0 / c))}


def lstm_initial_state(cfg: LSTMNetConfig, batch: int):
    z = jnp.zeros((batch, cfg.cell_size), jnp.float32)
    return (z, z)


def lstm_forward(params, x, carry, cfg: LSTMNetConfig):
    """x [B, T, D], carry (h, c) [B, cell] → ([B, T, cell], carry)."""

    def cell(carry, xt):
        h, c = carry
        gates = _dense(params["wx"], xt) + _dense(params["wh"], h)
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        c = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return (h, c), h

    carry, ys = lax.scan(cell, carry, x.transpose(1, 0, 2))
    return ys.transpose(1, 0, 2), carry


# -- GTrXL (reference: rllib/models/torch/attention_net.py) ----------------

@dataclass(frozen=True)
class GTrXLConfig:
    """Gated Transformer-XL trunk over an observation window."""
    in_dim: int
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128

    @property
    def out_dim(self) -> int:
        return self.d_model

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def gtrxl_init(cfg: GTrXLConfig, rng):
    keys = iter(jax.random.split(rng, 3 + 6 * cfg.n_layers))
    d, f = cfg.d_model, cfg.d_ff
    params = {"embed": _dense_init(next(keys), cfg.in_dim, d)}
    for i in range(cfg.n_layers):
        params[f"layer{i}"] = {
            "ln1_scale": jnp.ones((d,)), "ln1_bias": jnp.zeros((d,)),
            "wqkv": _dense_init(next(keys), d, 3 * d, scale=0.02),
            "wo": _dense_init(next(keys), d, d, scale=0.02),
            # GRU-style gating (the "G" in GTrXL) — see _gate for the
            # near-identity init
            "wg_attn": _dense_init(next(keys), 2 * d, d, scale=0.02),
            "ln2_scale": jnp.ones((d,)), "ln2_bias": jnp.zeros((d,)),
            "w_up": _dense_init(next(keys), d, f, scale=0.02),
            "w_down": _dense_init(next(keys), f, d, scale=0.02),
            "wg_mlp": _dense_init(next(keys), 2 * d, d, scale=0.02),
        }
    return params


def _gate(p, x, y):
    """Sigmoid gate (1-g)·x + g·y — simplified GRU gating.  The -2.0 bias
    makes g≈0.12 at init so each block starts near the identity/residual
    path (the GTrXL stability trick)."""
    g = jax.nn.sigmoid(_dense(p, jnp.concatenate([x, y], -1)) - 2.0)
    return (1 - g) * x + g * y


def gtrxl_forward(params, x, cfg: GTrXLConfig):
    """x [B, T, in_dim] → features [B, T, d_model].  Causal within the
    window (memory = the window itself; no cross-window cache)."""
    from ray_tpu.models.gpt import _layer_norm
    b, t, _ = x.shape
    h, hd = cfg.n_heads, cfg.head_dim
    x = _dense(params["embed"], x)
    for i in range(cfg.n_layers):
        lp = params[f"layer{i}"]
        y = _layer_norm(x, lp["ln1_scale"], lp["ln1_bias"])
        qkv = _dense(lp["wqkv"], y)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(z):
            return z.reshape(b, t, h, hd).transpose(0, 2, 1, 3)

        o = attention(heads(q), heads(k), heads(v), causal=True,
                      impl="reference")
        o = o.transpose(0, 2, 1, 3).reshape(b, t, cfg.d_model)
        o = jax.nn.relu(_dense(lp["wo"], o))
        x = _gate(lp["wg_attn"], x, o)

        y = _layer_norm(x, lp["ln2_scale"], lp["ln2_bias"])
        u = jax.nn.relu(_dense(lp["w_up"], y))
        dn = jax.nn.relu(_dense(lp["w_down"], u))
        x = _gate(lp["wg_mlp"], x, dn)
    return x


# -- actor-critic assembly (reference: rllib/models/catalog.py) ------------

@dataclass(frozen=True)
class ModelConfig:
    """Catalog config: pick a trunk by name, heads are attached by
    ActorCritic.  Mirrors the reference's model_config dict
    (rllib/models/catalog.py)."""
    kind: str = "fcnet"              # fcnet | visionnet | lstm | gtrxl
    obs_shape: tuple = (4,)
    num_actions: int = 2
    fcnet_hiddens: tuple = (256, 256)
    fcnet_activation: str = "tanh"
    conv_filters: tuple = ((16, 8, 4), (32, 4, 2))
    cell_size: int = 256
    attn_dim: int = 64
    attn_layers: int = 2


def _trunk_for(cfg: ModelConfig):
    if cfg.kind == "fcnet":
        c = FCNetConfig(int(np.prod(cfg.obs_shape)), cfg.fcnet_hiddens,
                        cfg.fcnet_activation)
        return c, fcnet_init, lambda p, x, c=c: fcnet_forward(p, x, c)
    if cfg.kind == "visionnet":
        c = VisionNetConfig(tuple(cfg.obs_shape), cfg.conv_filters)
        return c, visionnet_init, lambda p, x, c=c: visionnet_forward(p, x, c)
    if cfg.kind == "lstm":
        c = LSTMNetConfig(int(np.prod(cfg.obs_shape)), cfg.cell_size)
        return c, lstm_init, None   # recurrent: handled by caller
    if cfg.kind == "gtrxl":
        c = GTrXLConfig(int(np.prod(cfg.obs_shape)), cfg.attn_dim,
                        n_layers=cfg.attn_layers)
        return c, gtrxl_init, None  # sequence trunk: handled by caller
    raise ValueError(f"unknown model kind {cfg.kind!r}")


class ActorCritic:
    """Trunk + π/V heads; the unit the rllib policies consume.

    apply(params, obs) → (logits, value) for feedforward trunks;
    apply_seq(params, obs_seq, state) for lstm/gtrxl.
    """

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.trunk_cfg, self._trunk_init, self._trunk_fwd = _trunk_for(cfg)

    @property
    def is_recurrent(self) -> bool:
        return self.cfg.kind in ("lstm", "gtrxl")

    def init(self, rng):
        k1, k2, k3 = jax.random.split(rng, 3)
        f = self.trunk_cfg.out_dim
        return {"trunk": self._trunk_init(self.trunk_cfg, k1),
                "pi": _dense_init(k2, f, self.cfg.num_actions, scale=0.01),
                "vf": _dense_init(k3, f, 1, scale=1.0)}

    def initial_state(self, batch: int):
        if self.cfg.kind == "lstm":
            return lstm_initial_state(self.trunk_cfg, batch)
        return None

    def apply(self, params, obs):
        """Feedforward path: obs [B, ...] → (logits [B, A], value [B])."""
        if self.is_recurrent:
            raise ValueError(
                f"{self.cfg.kind} is recurrent/sequential — use "
                "apply_seq(params, obs[B, T, ...], state)")
        if self.cfg.kind == "visionnet":
            feats = visionnet_forward(params["trunk"], obs, self.trunk_cfg)
        else:
            obs = obs.reshape(obs.shape[0], -1)
            feats = self._trunk_fwd(params["trunk"], obs)
        logits = _dense(params["pi"], feats)
        value = _dense(params["vf"], feats)[:, 0]
        return logits, value

    def apply_seq(self, params, obs, state=None):
        """Sequence path: obs [B, T, ...] → (logits [B,T,A], value [B,T],
        new_state)."""
        b, t = obs.shape[:2]
        if self.cfg.kind == "visionnet":
            feats = visionnet_forward(
                params["trunk"], obs.reshape(b * t, *self.cfg.obs_shape),
                self.trunk_cfg).reshape(b, t, -1)
            logits = _dense(params["pi"], feats)
            value = _dense(params["vf"], feats)[..., 0]
            return logits, value, state
        flat = obs.reshape(b, t, -1)
        if self.cfg.kind == "lstm":
            state = state if state is not None else self.initial_state(b)
            feats, state = lstm_forward(params["trunk"], flat, state,
                                        self.trunk_cfg)
        elif self.cfg.kind == "gtrxl":
            feats = gtrxl_forward(params["trunk"], flat, self.trunk_cfg)
        else:
            feats = self._trunk_fwd(params["trunk"],
                                    flat.reshape(b * t, -1)).reshape(
                                        b, t, -1)
        logits = _dense(params["pi"], feats)
        value = _dense(params["vf"], feats)[..., 0]
        return logits, value, state
