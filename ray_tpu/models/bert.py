"""BERT: bidirectional transformer encoder, TPU-first (north-star #5:
HF BERT-base + PBT sweep on v5e-16).

Reference capability: the reference's HuggingFace Train integration
(python/ray/train/huggingface/) fine-tunes torch BERT; it ships no model
code.  Here the encoder is framework-owned and shares the GPT design:
plain pytree params with logical sharding axes, ``lax.scan`` over stacked
layers (O(1) compile in depth), pallas attention dispatch, bf16
activations / f32 accumulators, declarative dp/fsdp/tp sharding via the
same rule table (parallel/sharding.py) — no model rewrite per layout.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ray_tpu.models.gpt import _layer_norm  # shared f32 layernorm
from ray_tpu.ops.attention import attention
from ray_tpu.parallel.sharding import DEFAULT_LLM_RULES, Rules, spec_for


@dataclass(frozen=True)
class BERTConfig:
    vocab_size: int = 30592          # bert-base vocab padded to 128
    max_seq: int = 512
    type_vocab: int = 2
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    ignore_index: int = -100         # label value meaning "not an MLM target"
    attn_impl: Optional[str] = None  # None=auto (flash on TPU), "reference"
    pp_microbatches: Optional[int] = None  # None = 2*pp stages (GPipe)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @staticmethod
    def bert_base(**kw) -> "BERTConfig":
        return BERTConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "BERTConfig":
        return BERTConfig(**{**dict(vocab_size=512, max_seq=128, d_model=64,
                                    n_heads=4, n_layers=2, d_ff=128,
                                    remat=False, dtype=jnp.float32), **kw})


PARAM_AXES = {
    "wte": ("vocab", "embed"),
    "wpe": (None, "embed"),
    "wtype": (None, "embed"),
    "ln_emb_scale": ("embed",),
    "ln_emb_bias": ("embed",),
    "layers": {
        "wqkv": ("layers", "embed", "qkv"),
        "wo": ("layers", "heads", "embed"),
        "bo": ("layers", "embed"),
        "ln1_scale": ("layers", "embed"),
        "ln1_bias": ("layers", "embed"),
        "w_up": ("layers", "embed", "mlp"),
        "b_up": ("layers", "mlp"),
        "w_down": ("layers", "mlp", "embed"),
        "b_down": ("layers", "embed"),
        "ln2_scale": ("layers", "embed"),
        "ln2_bias": ("layers", "embed"),
    },
    "mlm_dense_w": ("embed", "embed"),
    "mlm_dense_b": ("embed",),
    "mlm_ln_scale": ("embed",),
    "mlm_ln_bias": ("embed",),
    "mlm_bias": ("vocab",),
    "pooler_w": ("embed", "embed"),
    "pooler_b": ("embed",),
}


def param_logical_axes(cfg: BERTConfig):
    return dict(PARAM_AXES)


def init_params(cfg: BERTConfig, rng: jax.Array):
    k = iter(jax.random.split(rng, 16))
    d, L, f = cfg.d_model, cfg.n_layers, cfg.d_ff
    pd, std = cfg.param_dtype, 0.02

    def norm(key, shape, s=std):
        return (jax.random.normal(key, shape) * s).astype(pd)

    return {
        "wte": norm(next(k), (cfg.vocab_size, d)),
        "wpe": norm(next(k), (cfg.max_seq, d)),
        "wtype": norm(next(k), (cfg.type_vocab, d)),
        "ln_emb_scale": jnp.ones((d,), pd),
        "ln_emb_bias": jnp.zeros((d,), pd),
        "layers": {
            "wqkv": norm(next(k), (L, d, 3 * d)),
            "wo": norm(next(k), (L, d, d), std / math.sqrt(2 * L)),
            "bo": jnp.zeros((L, d), pd),
            "ln1_scale": jnp.ones((L, d), pd),
            "ln1_bias": jnp.zeros((L, d), pd),
            "w_up": norm(next(k), (L, d, f)),
            "b_up": jnp.zeros((L, f), pd),
            "w_down": norm(next(k), (L, f, d), std / math.sqrt(2 * L)),
            "b_down": jnp.zeros((L, d), pd),
            "ln2_scale": jnp.ones((L, d), pd),
            "ln2_bias": jnp.zeros((L, d), pd),
        },
        "mlm_dense_w": norm(next(k), (d, d)),
        "mlm_dense_b": jnp.zeros((d,), pd),
        "mlm_ln_scale": jnp.ones((d,), pd),
        "mlm_ln_bias": jnp.zeros((d,), pd),
        "mlm_bias": jnp.zeros((cfg.vocab_size,), pd),
        "pooler_w": norm(next(k), (d, d)),
        "pooler_b": jnp.zeros((d,), pd),
    }


def _constrain(x, logical, mesh, rules):
    if mesh is None:
        return x
    spec = spec_for(logical, rules, mesh)
    return lax.with_sharding_constraint(
        x, jax.sharding.NamedSharding(mesh, spec))


def encode(params, tokens, cfg: BERTConfig, *,
           attention_mask: Optional[jax.Array] = None,
           token_type_ids: Optional[jax.Array] = None,
           mesh=None, rules: Rules = DEFAULT_LLM_RULES):
    """tokens [b, s] int32 → hidden [b, s, d] (cfg.dtype)."""
    b, s = tokens.shape
    h, hd = cfg.n_heads, cfg.head_dim

    x = params["wte"][tokens] + params["wpe"][:s][None, :, :]
    if token_type_ids is not None:
        x = x + params["wtype"][token_type_ids]
    x = _layer_norm(x.astype(cfg.dtype), params["ln_emb_scale"],
                    params["ln_emb_bias"])
    x = _constrain(x, ("batch", "seq", "embed"), mesh, rules)

    # [b, 1, 1, s] additive-style boolean mask broadcast over (h, q)
    attn_mask = None
    if attention_mask is not None:
        attn_mask = attention_mask[:, None, None, :].astype(bool)

    def layer(x, lp):
        bx, sx = x.shape[0], x.shape[1]  # microbatched under pp
        qkv = jnp.einsum("bsd,de->bse", x, lp["wqkv"].astype(cfg.dtype))
        qkv = _constrain(qkv, ("batch", "seq", "qkv"), mesh, rules)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(bx, sx, h, hd).transpose(0, 2, 1, 3)

        # auto-dispatch (pallas flash on TPU) when there is no padding
        # mask; the masked path needs the reference impl
        impl = "reference" if attn_mask is not None else cfg.attn_impl
        o = attention(heads(q), heads(k), heads(v), causal=False,
                      mask=attn_mask, impl=impl)
        o = o.transpose(0, 2, 1, 3).reshape(bx, sx, cfg.d_model)
        o = jnp.einsum("bsd,de->bse", o, lp["wo"].astype(cfg.dtype)) \
            + lp["bo"].astype(cfg.dtype)
        x = _layer_norm(x + o, lp["ln1_scale"], lp["ln1_bias"])  # post-LN
        x = _constrain(x, ("batch", "seq", "embed"), mesh, rules)

        u = jnp.einsum("bsd,df->bsf", x, lp["w_up"].astype(cfg.dtype)) \
            + lp["b_up"].astype(cfg.dtype)
        u = _constrain(u, ("batch", "seq", "mlp"), mesh, rules)
        u = jax.nn.gelu(u)
        dn = jnp.einsum("bsf,fd->bsd", u, lp["w_down"].astype(cfg.dtype)) \
            + lp["b_down"].astype(cfg.dtype)
        x = _layer_norm(x + dn, lp["ln2_scale"], lp["ln2_bias"])
        x = _constrain(x, ("batch", "seq", "embed"), mesh, rules)
        return x, None

    body = jax.checkpoint(layer) if cfg.remat else layer

    if mesh is not None and mesh.shape.get("pp", 1) > 1:
        # GPipe microbatch pipeline over pp (parallel.pipeline); the
        # encoder stack is residual-stream shaped so the generic stage
        # runner applies directly
        from ray_tpu.parallel.pipeline import pipeline_apply
        if attn_mask is not None:
            raise NotImplementedError(
                "attention_mask + pp pipeline is not supported yet; "
                "pad-free batches only on pp meshes")
        S = mesh.shape["pp"]
        if cfg.n_layers % S != 0:
            raise ValueError(
                f"n_layers {cfg.n_layers} not divisible by pp={S}")
        M = cfg.pp_microbatches or 2 * S
        if b % M != 0:
            raise ValueError(f"batch {b} not divisible by microbatches {M}")
        x_mb = x.reshape(M, b // M, s, cfg.d_model)

        def stage_fn(local_layers, xm):
            xm, _ = lax.scan(body, xm, local_layers)
            return xm

        outs = pipeline_apply(stage_fn, x_mb, params["layers"], mesh=mesh)
        return outs.reshape(b, s, cfg.d_model)

    x, _ = lax.scan(body, x, params["layers"])
    return x


def mlm_logits(params, hidden, cfg: BERTConfig):
    """MLM head: dense+gelu+LN then tied-embedding projection."""
    y = jnp.einsum("bsd,de->bse", hidden,
                   params["mlm_dense_w"].astype(hidden.dtype)) \
        + params["mlm_dense_b"].astype(hidden.dtype)
    y = jax.nn.gelu(y)
    y = _layer_norm(y, params["mlm_ln_scale"], params["mlm_ln_bias"])
    logits = jnp.einsum("bsd,vd->bsv", y, params["wte"].astype(y.dtype))
    return logits.astype(jnp.float32) + params["mlm_bias"].astype(jnp.float32)


def pool(params, hidden):
    """[CLS] pooler: tanh(dense(hidden[:, 0]))."""
    cls = hidden[:, 0, :]
    return jnp.tanh(cls @ params["pooler_w"].astype(cls.dtype)
                    + params["pooler_b"].astype(cls.dtype))


def loss_fn(params, batch, cfg: BERTConfig, *, mesh=None,
            rules: Rules = DEFAULT_LLM_RULES):
    """Masked-LM cross-entropy.  batch = {"input_ids": [b,s] int32,
    "labels": [b,s] int32 with ignore_index where not masked,
    optional "attention_mask": [b,s]}."""
    hidden = encode(params, batch["input_ids"], cfg,
                    attention_mask=batch.get("attention_mask"),
                    token_type_ids=batch.get("token_type_ids"),
                    mesh=mesh, rules=rules)
    logits = mlm_logits(params, hidden, cfg)
    labels = batch["labels"]
    valid = labels != cfg.ignore_index
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, logz - gold, 0.0)
    return jnp.sum(nll) / jnp.maximum(jnp.sum(valid), 1)


def num_params(params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


class BERT:
    """OO convenience wrapper over the functional API."""

    def __init__(self, cfg: BERTConfig):
        self.cfg = cfg

    def init(self, rng):
        return init_params(self.cfg, rng)

    def logical_axes(self):
        return param_logical_axes(self.cfg)

    def encode(self, params, tokens, **kw):
        return encode(params, tokens, self.cfg, **kw)

    def loss(self, params, batch, **kw):
        return loss_fn(params, batch, self.cfg, **kw)
