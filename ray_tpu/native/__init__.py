"""Native (C++) runtime components, loaded via ctypes.

The image has no pybind11, so Python reaches the C++ runtime through a
plain C ABI (reference reaches its C++ CoreWorker through one Cython
module, python/ray/_raylet.pyx:1490 — here the binding is ctypes over
extern "C").  The shared library is built on demand with `make` (g++ is
in the image); the build is cached next to this package.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_LIB_NAME = "librt_store.so"
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))
_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(_PKG_DIR)),
                           "native")
_lock = threading.Lock()
_lib = None
_load_error: Exception | None = None


def _build() -> None:
    subprocess.run(["make", "-s", "all"], cwd=_NATIVE_DIR, check=True,
                   capture_output=True)


def load_library() -> ctypes.CDLL:
    """Load (building if needed) librt_store.so; raises on failure."""
    global _lib, _load_error
    with _lock:
        if _lib is not None:
            return _lib
        if _load_error is not None:
            raise _load_error
        path = os.path.join(_PKG_DIR, _LIB_NAME)
        try:
            if not os.path.exists(path):
                _build()
            lib = ctypes.CDLL(path)
            _declare(lib)
            _lib = lib
            return lib
        except Exception as e:  # missing toolchain, bad arch, ...
            _load_error = e
            raise


def available() -> bool:
    try:
        load_library()
        return True
    except Exception:
        return False


def _declare(lib: ctypes.CDLL) -> None:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.rt_store_create.restype = ctypes.c_void_p
    lib.rt_store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                    ctypes.c_uint32]
    lib.rt_store_attach.restype = ctypes.c_void_p
    lib.rt_store_attach.argtypes = [ctypes.c_char_p]
    lib.rt_store_detach.argtypes = [ctypes.c_void_p]
    lib.rt_store_destroy.argtypes = [ctypes.c_char_p]
    lib.rt_store_destroy.restype = ctypes.c_int
    lib.rt_store_map_bytes.restype = ctypes.c_uint64
    lib.rt_store_map_bytes.argtypes = [ctypes.c_void_p]
    lib.rt_obj_create.restype = ctypes.c_int64
    lib.rt_obj_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_uint64]
    lib.rt_obj_seal.restype = ctypes.c_int
    lib.rt_obj_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_obj_get.restype = ctypes.c_int64
    lib.rt_obj_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_obj_lookup.restype = ctypes.c_int64
    lib.rt_obj_lookup.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64)]
    lib.rt_obj_release.restype = ctypes.c_int
    lib.rt_obj_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_obj_delete.restype = ctypes.c_int
    lib.rt_obj_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_obj_contains.restype = ctypes.c_int
    lib.rt_obj_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_obj_refcount.restype = ctypes.c_uint64
    lib.rt_obj_refcount.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.rt_evict_candidates.restype = ctypes.c_int
    lib.rt_evict_candidates.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                        u8p, ctypes.c_int]
    lib.rt_store_used.restype = ctypes.c_uint64
    lib.rt_store_used.argtypes = [ctypes.c_void_p]
    lib.rt_store_capacity.restype = ctypes.c_uint64
    lib.rt_store_capacity.argtypes = [ctypes.c_void_p]
    lib.rt_store_num_objects.restype = ctypes.c_uint64
    lib.rt_store_num_objects.argtypes = [ctypes.c_void_p]
