"""Python client for the native shm arena store (native/src/shm_store.cc).

One arena per session on the host; every process maps it once and reads
objects as zero-copy slices.  Lifetime safety for zero-copy reads: `get`
takes a native refcount and ties its release to the garbage collection
of a numpy wrapper that every deserialized view transitively references
(the capability the reference gets from plasma client buffer tracking,
src/ray/object_manager/plasma/client.cc).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import time
import weakref
from typing import Optional

import numpy as np

from ray_tpu import native as _native

ID_SIZE = 28

RT_ERR_EXISTS = -1
RT_ERR_OOM = -2
RT_ERR_NOT_FOUND = -3
RT_ERR_NOT_SEALED = -4
RT_ERR_IN_USE = -5


class NativeStoreError(RuntimeError):
    pass


class NativeStoreFull(NativeStoreError):
    pass


class NativeObjectExists(NativeStoreError):
    """A sealed object with this id already exists (idempotent re-put)."""


def _check_id(id_bytes: bytes) -> bytes:
    if len(id_bytes) != ID_SIZE:
        raise ValueError(f"object id must be {ID_SIZE} bytes")
    return id_bytes


class NativeArena:
    """Per-process handle to the session's shm arena."""

    def __init__(self, name: str, capacity: Optional[int] = None,
                 create: bool = False, table_slots: int = 1 << 16):
        self._lib = _native.load_library()
        self._name = name.encode()
        if create:
            assert capacity is not None
            self._h = self._lib.rt_store_create(self._name, capacity,
                                                table_slots)
        else:
            self._h = self._lib.rt_store_attach(self._name)
        if not self._h:
            raise NativeStoreError(f"cannot open arena {name!r}")
        # map the data plane: /dev/shm/<name> is the same segment
        nbytes = self._lib.rt_store_map_bytes(self._h)
        fd = os.open(f"/dev/shm/{name}", os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, nbytes)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)
        # id -> outstanding native refs taken by this process (released
        # on finalizer or shutdown)
        self._refs: dict[bytes, int] = {}

    # -- object ops --------------------------------------------------------

    def create(self, id_bytes: bytes, size: int) -> memoryview:
        """Allocate and return a writable view (seal when done)."""
        _check_id(id_bytes)
        off = self._lib.rt_obj_create(self._h, id_bytes, size)
        if off == RT_ERR_OOM:
            raise NativeStoreFull(size)
        if off == RT_ERR_EXISTS:
            # Deterministic ids: a retried task re-creates its returns.
            # SEALED → the value is already here (task determinism):
            # idempotent no-op for the caller.  CREATED → the first
            # attempt died mid-write (a live writer is never concurrent
            # with a retry: the node doesn't double-dispatch); unsealed
            # objects can carry no read refs, so delete succeeds and we
            # allocate fresh.
            if self.contains(id_bytes) == 2:  # RT_STATE_SEALED
                raise NativeObjectExists(id_bytes.hex())
            self._lib.rt_obj_delete(self._h, id_bytes)
            off = self._lib.rt_obj_create(self._h, id_bytes, size)
            if off == RT_ERR_OOM:
                raise NativeStoreFull(size)
        if off < 0:
            raise NativeStoreError(f"create failed: {off}")
        return self._view[off:off + size]

    def seal(self, id_bytes: bytes) -> None:
        self._lib.rt_obj_seal(self._h, _check_id(id_bytes))

    def get(self, id_bytes: bytes) -> Optional[np.ndarray]:
        """Zero-copy read of a sealed object.

        Returns a uint8 ndarray over the arena.  A native reference is
        held until the array (and every view derived from it) is GC'd.
        """
        _check_id(id_bytes)
        size = ctypes.c_uint64()
        off = self._lib.rt_obj_get(self._h, id_bytes, ctypes.byref(size))
        if off < 0:
            return None
        self._refs[id_bytes] = self._refs.get(id_bytes, 0) + 1
        arr = np.frombuffer(self._view, dtype=np.uint8,
                            count=size.value, offset=off)
        weakref.finalize(arr, self._release_cb, id_bytes)
        return arr

    def _release_cb(self, id_bytes: bytes) -> None:
        if not self._h:
            return  # finalizer fired after detach
        n = self._refs.get(id_bytes, 0)
        if n <= 0:
            return
        if n == 1:
            self._refs.pop(id_bytes, None)
        else:
            self._refs[id_bytes] = n - 1
        try:
            self._lib.rt_obj_release(self._h, id_bytes)
        except Exception:
            pass

    def lookup(self, id_bytes: bytes) -> Optional[memoryview]:
        """Refcount-free view (node-side spill; caller must hold a pin)."""
        size = ctypes.c_uint64()
        off = self._lib.rt_obj_lookup(self._h, _check_id(id_bytes),
                                      ctypes.byref(size))
        if off < 0:
            return None
        return self._view[off:off + size.value]

    def delete(self, id_bytes: bytes) -> bool:
        return self.delete_rc(id_bytes) == 0

    def delete_rc(self, id_bytes: bytes) -> int:
        """Delete returning the raw status (0, RT_ERR_IN_USE, ...)."""
        return self._lib.rt_obj_delete(self._h, _check_id(id_bytes))

    def contains(self, id_bytes: bytes) -> int:
        return self._lib.rt_obj_contains(self._h, _check_id(id_bytes))

    def refcount(self, id_bytes: bytes) -> int:
        return self._lib.rt_obj_refcount(self._h, _check_id(id_bytes))

    def evict_candidates(self, nbytes: int, max_out: int = 256) -> list[bytes]:
        buf = (ctypes.c_uint8 * (ID_SIZE * max_out))()
        n = self._lib.rt_evict_candidates(self._h, nbytes, buf, max_out)
        raw = bytes(buf)
        return [raw[i * ID_SIZE:(i + 1) * ID_SIZE] for i in range(n)]

    # -- stats / lifecycle -------------------------------------------------

    @property
    def used(self) -> int:
        return self._lib.rt_store_used(self._h)

    @property
    def capacity(self) -> int:
        return self._lib.rt_store_capacity(self._h)

    @property
    def num_objects(self) -> int:
        return self._lib.rt_store_num_objects(self._h)

    def detach(self) -> None:
        if self._h:
            # Entries still in _refs back zero-copy views that are ALIVE
            # in this process — releasing them would let another process
            # reuse the memory under the live view (silent corruption).
            # Leak the refcounts instead; the node defers those deletes
            # and the arena is destroyed with the session anyway.
            self._refs.clear()
            self._lib.rt_store_detach(self._h)
            self._h = None
            try:
                self._view.release()
                self._mm.close()
            except BufferError:
                pass  # zero-copy views still alive; freed at process exit

    def destroy(self) -> None:
        name = self._name
        self.detach()
        self._lib.rt_store_destroy(name)


def attach_with_retry(name: str, timeout: float = 5.0) -> NativeArena:
    """Attach, waiting for the node service to create the arena."""
    deadline = time.monotonic() + timeout
    while True:
        try:
            return NativeArena(name)
        except NativeStoreError:
            if time.monotonic() > deadline:
                raise
            time.sleep(0.01)
