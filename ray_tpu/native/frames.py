"""ctypes binding for the native frame codec (native/src/rt_frames.cc).

Two handles to one shared library:

* ``ctypes.PyDLL`` for the codec entry points — they take and return
  real ``PyObject*``, so the GIL must stay held and one call encodes a
  whole message with no per-field ctypes overhead (the same in-process
  trick the shm store uses for its C ABI, minus the GIL release).
* ``ctypes.CDLL`` for the MPSC ring's push/pending — plain C pointers,
  so ctypes drops the GIL around the memcpy like any foreign call.

Import of this module must stay side-effect free on failure: the codec
arming surface (``core/rt_frames.py``) treats any exception here as
"stay on the pickle path".
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Optional

_LIB_NAME = "librt_frames.so"
_PKG_DIR = os.path.dirname(os.path.abspath(__file__))

_lock = threading.Lock()
_libs: Optional[tuple] = None


def load_libraries() -> tuple:
    """(PyDLL, CDLL) over librt_frames.so; raises when absent.

    Unlike the store loader this never builds on demand: arming happens
    at import time on every process, and a missing .so must mean "use
    the pure-Python pickle path", not "run the compiler" (the
    forced-fallback tests depend on exactly that)."""
    global _libs
    with _lock:
        if _libs is not None:
            return _libs
        # RAY_TPU_FRAMES_LIB: test hook — point the loader somewhere
        # else (e.g. a nonexistent path) to exercise the exact
        # missing-.so fallback without touching the committed library
        path = os.environ.get("RAY_TPU_FRAMES_LIB") \
            or os.path.join(_PKG_DIR, _LIB_NAME)
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        pylib = ctypes.PyDLL(path)
        clib = ctypes.CDLL(path)
        if clib.rtf_abi_version() != 1:
            raise RuntimeError("librt_frames.so ABI mismatch")
        _declare(pylib, clib)
        _libs = (pylib, clib)
        return _libs


def available() -> bool:
    try:
        load_libraries()
        return True
    except Exception:
        return False


def _declare(pylib: ctypes.PyDLL, clib: ctypes.CDLL) -> None:
    pylib.rtf_encode_frame.restype = ctypes.py_object
    pylib.rtf_encode_frame.argtypes = [ctypes.py_object, ctypes.c_char_p,
                                       ctypes.c_double]
    pylib.rtf_decode_payload.restype = ctypes.py_object
    pylib.rtf_decode_payload.argtypes = [ctypes.py_object]
    pylib.rtf_ring_drain_py.restype = ctypes.py_object
    pylib.rtf_ring_drain_py.argtypes = [ctypes.c_void_p]

    clib.rtf_ring_new.restype = ctypes.c_void_p
    clib.rtf_ring_new.argtypes = [ctypes.c_uint64]
    clib.rtf_ring_free.argtypes = [ctypes.c_void_p]
    clib.rtf_ring_push.restype = ctypes.c_int
    clib.rtf_ring_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_uint64]
    clib.rtf_ring_pending.restype = ctypes.c_uint64
    clib.rtf_ring_pending.argtypes = [ctypes.c_void_p]
    clib.rtf_validate.restype = ctypes.c_int
    clib.rtf_validate.argtypes = [ctypes.c_char_p, ctypes.c_uint64]


class NativeRing:
    """Send-combining MPSC ring: any thread pushes completed frames;
    whoever holds the owning connection's send lock drains them in one
    buffer.  Push returns False when the ring is full — the caller then
    takes its ordinary locked send path (after draining, for order)."""

    def __init__(self, pylib, clib, capacity: int):
        self._pylib = pylib
        self._clib = clib
        self._h = clib.rtf_ring_new(capacity)
        if not self._h:
            raise MemoryError("rtf_ring_new failed")

    def push(self, frame: bytes) -> bool:
        return self._clib.rtf_ring_push(self._h, frame, len(frame)) == 0

    def pending(self) -> int:
        return self._clib.rtf_ring_pending(self._h)

    def drain(self) -> bytes:
        return self._pylib.rtf_ring_drain_py(self._h)

    def close(self) -> None:
        h, self._h = self._h, None
        if h:
            self._clib.rtf_ring_free(h)

    def __del__(self):  # best-effort; close() is the real path
        try:
            self.close()
        except Exception:
            pass


class NativeFrameCodec:
    """The armed object behind ``rt_frames._active``."""

    def __init__(self):
        self._pylib, self._clib = load_libraries()
        self._enc = self._pylib.rtf_encode_frame
        self._dec = self._pylib.rtf_decode_payload

    def encode_frame(self, msg: dict, stamp: Optional[str] = None,
                     now: float = -1.0) -> Optional[bytes]:
        """dict → complete wire frame (8-byte header + 0x03 payload) in
        one C call, or None when the message needs pickle.  ``stamp``
        folds a flight-recorder ``(stage, t_monotonic)`` entry into the
        first ``"fr"`` list while encoding; ``now < 0`` reads
        CLOCK_MONOTONIC in C (tests pass a fixed value for parity)."""
        return self._enc(msg,
                         stamp.encode() if stamp is not None else None,
                         now)

    def decode_payload(self, data) -> dict:
        """Tagged 0x03 payload → dict (raises ValueError when
        malformed)."""
        if type(data) is memoryview:
            # PyBUF_SIMPLE needs C-contiguity; recv buffers always are
            return self._dec(data)
        return self._dec(bytes(data) if not isinstance(data, bytes)
                         else data)

    def validate(self, payload: bytes) -> int:
        return self._clib.rtf_validate(payload, len(payload))

    def make_ring(self, capacity: int = 1 << 20) -> NativeRing:
        return NativeRing(self._pylib, self._clib, capacity)
