"""Runtime environments: per-job/task execution context.

Reference capability: python/ray/_private/runtime_env/ —
``env_vars`` (applied around execution), ``working_dir`` and
``py_modules`` (zipped, content-addressed in the cluster KV store,
materialized into a worker-local cache and put on sys.path —
reference: runtime_env/working_dir.py + packaging.py + py_modules.py),
and ``pip`` (reference: runtime_env/pip.py): requirements installed
into a per-env-hash target directory that workers share and reuse.
Local wheel files (in ``pip`` or ``py_modules``) are content-addressed
through the cluster KV like directories, so the install path is fully
offline-capable; named requirement strings shell out to pip and need
an index (or a pre-populated cache) to resolve.

Worker reuse: envs are cached on disk by content hash, and the node
scheduler prefers dispatching a task to a worker that has already
materialized the same env hash (reference: worker_pool.h:192 caching
of workers per runtime-env hash).
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import zipfile
from typing import Any, Optional

_MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules",
                 ".pytest_cache", ".mypy_cache"}


def validate(runtime_env: dict) -> dict:
    known = {"env_vars", "working_dir", "py_modules", "pip", "conda",
             "container"}
    unknown = set(runtime_env) - known
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; supported: "
            f"{sorted(known)}")
    container = runtime_env.get("container")
    if container is not None:
        if (not isinstance(container, dict)
                or not isinstance(container.get("image"), str)
                or not container["image"]):
            raise ValueError(
                "container must be {'image': <str>, 'run_options': "
                "[...]} (reference: _private/runtime_env/container.py)")
        ro = container.get("run_options") or []
        if not all(isinstance(o, str) for o in ro):
            raise ValueError("container run_options must be strings")
    conda = runtime_env.get("conda")
    if conda is not None and not isinstance(conda, (str, dict)):
        raise ValueError(
            "conda must be an env name, a path to an environment.yml, "
            "or an environment dict (reference: "
            "_private/runtime_env/conda.py shapes)")
    ev = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("env_vars must be str -> str")
    pip = runtime_env.get("pip")
    if pip is not None:
        # accept the reference's shapes: list[str] or {"packages": [...]}
        if isinstance(pip, dict):
            pip = list(pip.get("packages") or [])
        elif isinstance(pip, str):
            pip = [pip]
        else:
            pip = list(pip)
        if not all(isinstance(p, str) for p in pip):
            raise ValueError("pip must be a list of requirement strings "
                             "or local wheel paths")
        runtime_env["pip"] = pip
    return runtime_env


def container_runtime() -> Optional[str]:
    """podman preferred, docker fallback (reference:
    _private/runtime_env/container.py uses podman)."""
    import shutil
    for rt in ("podman", "docker"):
        if shutil.which(rt):
            return rt
    return None


def container_command(container: dict, worker_cmd: list,
                      session_dir: str,
                      runtime: Optional[str] = None) -> list:
    """argv that launches a worker INSIDE the requested image: host
    network (the node's control socket), host IPC (the shm object
    store), and the session dir mounted through (logs, spill, sockets).
    Raises when no container runtime exists — at SPAWN time, with the
    real problem named."""
    rt = runtime or container_runtime()
    if rt is None:
        raise RuntimeError(
            "runtime_env requests a container but neither podman nor "
            "docker is installed on this node")
    # --pid=host: the worker registers os.getpid() with the node, and
    # every node-side signal (OOM kill, stack dump, chaos kills) targets
    # that pid on the HOST — a private pid namespace would make the node
    # signal the wrong process (or init) for every one of them
    return ([rt, "run", "--rm", "--network=host", "--ipc=host",
             "--pid=host",
             "-v", f"{session_dir}:{session_dir}",
             "-v", "/dev/shm:/dev/shm",
             "-e", f"RAY_TPU_CONTAINER_IMAGE={container['image']}"]
            + list(container.get("run_options") or [])
            + [container["image"]] + list(worker_cmd))


def env_hash(runtime_env: Optional[dict]) -> str:
    """Stable content hash of a PREPARED runtime env (local artifacts
    already content-addressed) — the worker-caching key (reference:
    worker_pool.h runtime_env_hash)."""
    if not runtime_env:
        return ""
    canon = json.dumps(
        {k: runtime_env[k] for k in sorted(runtime_env)
         if runtime_env[k] is not None},
        sort_keys=True, default=str)
    return hashlib.sha256(canon.encode()).hexdigest()[:16]


def _upload_wheel(client, path: str) -> str:
    """Content-address a local wheel file; returns a 'whl:' ref that
    workers can materialize anywhere in the cluster."""
    with open(path, "rb") as f:
        data = f.read()
    h = package_hash(data)
    key = f"runtime_env:pkg:{h}".encode()
    if _kv_missing(client, key):
        client.kv_put(key, data)
    return f"whl:{h}:{os.path.basename(path)}"


def prepare(runtime_env: dict, client) -> dict:
    """Submission-side step: upload every LOCAL artifact (directories,
    wheel files) into the cluster KV so any node can materialize the
    env (reference: packaging.py upload_package_if_needed called from
    the runtime-env agent)."""
    env = dict(runtime_env)
    wd = env.get("working_dir")
    if wd and os.path.isdir(wd):
        env["working_dir"] = upload_package(client, package_directory(wd))
    mods = env.get("py_modules")
    if mods:
        out = []
        for m in ([mods] if isinstance(mods, str) else list(mods)):
            if os.path.isdir(m):
                out.append(upload_package(client, package_directory(m)))
            elif m.endswith(".whl") and os.path.isfile(m):
                out.append(_upload_wheel(client, m))
            else:
                out.append(m)
        env["py_modules"] = out
    pip = env.get("pip")
    if pip:
        env["pip"] = [
            _upload_wheel(client, p)
            if p.endswith(".whl") and os.path.isfile(p) else p
            for p in pip]
    conda = env.get("conda")
    if isinstance(conda, str) and conda.endswith((".yml", ".yaml")):
        if not os.path.isfile(conda):
            # fail at SUBMISSION with the real problem, not worker-side
            # with a FileNotFoundError naming the submitter's path
            raise ValueError(
                f"runtime_env conda spec file not found: {conda!r}")
        # inline the spec text so remote nodes never need the
        # submitter's filesystem
        with open(conda) as f:
            env["conda"] = {"__environment_yaml__": f.read()}
    return env


def _materialize_wheel(client, ref: str, cache_root: str) -> str:
    """'whl:<hash>:<basename>' → local wheel file path."""
    _, h, basename = ref.split(":", 2)
    dest_dir = os.path.join(cache_root, "wheels", h)
    dest = os.path.join(dest_dir, basename)
    if os.path.exists(dest):
        return dest
    data = client.kv_get(f"runtime_env:pkg:{h}".encode())
    if data is None:
        raise RuntimeError(f"runtime_env wheel {h} not found in the "
                           "cluster KV store")
    os.makedirs(dest_dir, exist_ok=True)
    tmp = dest + f".tmp{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, dest)
    return dest


def _extract_wheel(whl_path: str, cache_root: str) -> str:
    """Extract a wheel into the cache, keyed by content hash; returns
    the importable directory."""
    with open(whl_path, "rb") as f:
        h = package_hash(f.read())
    path = os.path.join(cache_root, "whl_x", h)
    if os.path.isdir(path):
        return path
    tmp = path + f".tmp{os.getpid()}"
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with zipfile.ZipFile(whl_path) as z:
        z.extractall(tmp)
    try:
        os.replace(tmp, path)
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    return path


def _install_once(target: str, install, what: str) -> str:
    """Create ``target`` once per host: the first process runs
    ``install()`` then drops a .ready marker; racers wait on it.  The
    lock records the installer's pid so a SIGKILLed installer (e.g. the
    OOM monitor) can't deadlock the env forever — waiters steal a lock
    whose owner is dead."""
    marker = os.path.join(target, ".ready")
    if os.path.exists(marker):
        return target
    os.makedirs(target, exist_ok=True)
    lock = os.path.join(target, ".lock")

    def acquire() -> bool:
        try:
            fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            os.write(fd, str(os.getpid()).encode())
            os.close(fd)
            return True
        except FileExistsError:
            return False

    if not acquire():
        import time
        deadline = time.time() + 300
        while True:
            if os.path.exists(marker):
                return target
            try:
                with open(lock) as f:
                    owner = int(f.read().strip() or 0)
            except (OSError, ValueError):
                owner = 0
            alive = False
            if owner:
                try:
                    os.kill(owner, 0)
                    alive = True
                except OSError:
                    alive = False
            if not alive:
                # stale lock: remove and try to take over the install
                try:
                    os.remove(lock)
                except OSError:
                    pass
                if acquire():
                    break
            if time.time() > deadline:
                raise RuntimeError("timed out waiting for a concurrent "
                                   f"install of {what}")
            time.sleep(0.2)
    try:
        install()
        open(marker, "w").close()
    finally:
        try:
            os.remove(lock)
        except OSError:
            pass
    return target


def ensure_pip_env(client, pip: list, cache_root: Optional[str] = None,
                   ) -> str:
    """Install a pip requirement list into a per-hash target directory,
    once per cluster host (reference: pip.py PipProcessor; --target
    keeps the base environment untouched).  Local-wheel refs install
    with --no-index, so the path is offline-capable."""
    cache_root = cache_root or os.path.join("/tmp/ray_tpu",
                                            "runtime_env_cache")
    h = hashlib.sha256(json.dumps(sorted(pip)).encode()).hexdigest()[:16]
    target = os.path.join(cache_root, "pip", h)

    def install():
        wheels = [_materialize_wheel(client, p, cache_root)
                  for p in pip if p.startswith("whl:")]
        named = [p for p in pip if not p.startswith("whl:")]
        base = [sys.executable, "-m", "pip", "install", "--quiet",
                "--no-warn-script-location", "--target", target]
        try:
            if wheels:
                subprocess.run(base + ["--no-index", "--no-deps"] + wheels,
                               check=True, capture_output=True, text=True)
            if named:
                subprocess.run(base + named, check=True,
                               capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"pip install failed for {pip}: {e.stderr}") from e

    return _install_once(target, install, f"pip {pip}")


# -- conda environments ------------------------------------------------------
# (reference: _private/runtime_env/conda.py — named envs activate an
# existing environment; dict/yaml specs create one per env hash)

# per-process cache of named-env prefix resolutions
_named_env_prefixes: dict[str, str] = {}


def _conda_exe() -> str:
    import shutil
    exe = shutil.which("conda")
    if exe is None:
        raise RuntimeError(
            "runtime_env 'conda' requires the conda CLI on every node; "
            "it is not on PATH")
    return exe


def conda_site_packages(prefix: str) -> Optional[str]:
    import glob
    cands = sorted(glob.glob(os.path.join(prefix, "lib", "python*",
                                          "site-packages")))
    return cands[0] if cands else None


def _check_conda_python_compat(prefix: str) -> None:
    """This runtime activates conda envs by site-packages injection into
    the RUNNING worker interpreter (the reference re-execs the env's own
    python) — so an env pinning a different python would import
    wrong-ABI extensions.  Fail with the real story instead."""
    import re
    sp = conda_site_packages(prefix)
    if not sp:
        return
    m = re.search(r"python(\d+)\.(\d+)", sp)
    if m and (int(m.group(1)), int(m.group(2))) != sys.version_info[:2]:
        raise RuntimeError(
            f"conda env at {prefix} provides python "
            f"{m.group(1)}.{m.group(2)} but this cluster's workers run "
            f"{sys.version_info[0]}.{sys.version_info[1]}; pin the same "
            "python in the env spec (activation injects site-packages "
            "into the running interpreter)")


def _emit_environment_yaml(spec: dict) -> str:
    """Minimal YAML emitter for the environment.yml shapes conda
    accepts (name/channels/dependencies with one level of pip nesting)
    — avoids a hard pyyaml dependency."""
    lines = []
    if spec.get("name"):
        lines.append(f"name: {spec['name']}")
    for key in ("channels", "dependencies"):
        vals = spec.get(key)
        if not vals:
            continue
        lines.append(f"{key}:")
        for v in vals:
            if isinstance(v, dict):   # {"pip": [...]} nested block
                for k2, sub in v.items():
                    lines.append(f"  - {k2}:")
                    for s in sub:
                        lines.append(f"    - {s}")
            else:
                lines.append(f"  - {v}")
    return "\n".join(lines) + "\n"


def ensure_conda_env(client, conda, cache_root: Optional[str] = None,
                     ) -> str:
    """Resolve/create the conda env; returns its PREFIX path.

    str (not *.yml) — a named env that must already exist on the node;
    str *.yml / *.yaml — a spec file (prepare() inlines its text so
    remote nodes don't need the submitter's filesystem);
    dict — an environment spec, created once per hash per host."""
    cache_root = cache_root or os.path.join("/tmp/ray_tpu",
                                            "runtime_env_cache")
    exe = _conda_exe()
    if isinstance(conda, str) and not conda.endswith((".yml", ".yaml")):
        # applied_env runs per task: cache the resolved prefix so the
        # hot path doesn't shell out `conda env list` every execution
        cached = _named_env_prefixes.get(conda)
        if cached is not None:
            return cached
        out = subprocess.run([exe, "env", "list", "--json"], check=True,
                             capture_output=True, text=True)
        envs = json.loads(out.stdout or "{}").get("envs", [])
        if conda == "base":
            # the base env IS the install prefix (its basename is the
            # distribution dir, not "base"): it's the entry not nested
            # under any <root>/envs/
            roots = [p for p in envs if f"{os.sep}envs{os.sep}" not in p]
            if roots:
                _named_env_prefixes[conda] = roots[0]
                return roots[0]
        for p in envs:
            if os.path.basename(p) == conda:
                _named_env_prefixes[conda] = p
                return p
        raise RuntimeError(f"conda env {conda!r} not found on this node")

    if isinstance(conda, str):
        with open(conda) as f:
            spec_text = f.read()
    elif "__environment_yaml__" in conda:
        spec_text = conda["__environment_yaml__"]
    else:
        spec_text = _emit_environment_yaml(conda)

    h = hashlib.sha256(spec_text.encode()).hexdigest()[:16]
    target = os.path.join(cache_root, "conda", h)
    prefix = os.path.join(target, "env")

    def install():
        spec_file = os.path.join(target, "environment.yml")
        with open(spec_file, "w") as f:
            f.write(spec_text)
        # a previous attempt may have died mid-create; conda refuses to
        # create into a non-empty prefix, so clear the debris first
        import shutil
        shutil.rmtree(prefix, ignore_errors=True)
        try:
            subprocess.run([exe, "env", "create", "-q", "-p", prefix,
                            "-f", spec_file],
                           check=True, capture_output=True, text=True)
        except subprocess.CalledProcessError as e:
            raise RuntimeError(
                f"conda env create failed: {e.stderr}") from e

    _install_once(target, install, "conda env")
    return prefix


def package_directory(path: str) -> bytes:
    """Zip a directory deterministically (reference:
    packaging.py create_package)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                # from_file keeps permission bits (exec scripts survive
                # extraction); the pinned date keeps the hash stable
                info = zipfile.ZipInfo.from_file(full, rel)
                info.date_time = (1980, 1, 1, 0, 0, 0)
                with open(full, "rb") as fh:
                    z.writestr(info, fh.read())
    return buf.getvalue()


def package_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:32]


def _kv_missing(client, key: bytes) -> bool:
    """Existence check WITHOUT transferring the payload back."""
    try:
        return not client.kv_keys(prefix=key)
    except Exception:
        return client.kv_get(key) is None


def upload_package(client, data: bytes) -> str:
    """Content-addressed upload into the cluster KV (reference:
    packaging.py upload_package_if_needed).  Returns the package hash."""
    h = package_hash(data)
    key = f"runtime_env:pkg:{h}".encode()
    if _kv_missing(client, key):
        client.kv_put(key, data)
    return h


def ensure_package(client, pkg_hash: str,
                   cache_root: Optional[str] = None) -> str:
    """Materialize a package into the local cache; idempotent
    (reference: working_dir.py download_and_unpack_package)."""
    cache_root = cache_root or os.path.join(
        "/tmp/ray_tpu", "runtime_env_cache")
    dest = os.path.join(cache_root, pkg_hash)
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return dest
    data = client.kv_get(f"runtime_env:pkg:{pkg_hash}".encode())
    if data is None:
        raise RuntimeError(f"runtime_env package {pkg_hash} not found "
                           "in the cluster KV store")
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
        # extractall drops permission bits — restore them so bundled
        # scripts/binaries stay executable
        for info in z.infolist():
            mode = (info.external_attr >> 16) & 0o7777
            if mode:
                try:
                    os.chmod(os.path.join(tmp, info.filename), mode)
                except OSError:
                    pass
    try:
        os.replace(tmp, dest)   # atomic against racing workers
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    open(marker, "w").close()
    return dest


class applied_env:
    """Context manager applying a runtime_env around execution
    (env_vars set/restored; working_dir/py_modules on sys.path + cwd)."""

    def __init__(self, runtime_env: Optional[dict], client=None):
        self.env = runtime_env or {}
        self.client = client
        self._saved_env: dict[str, Optional[str]] = {}
        self.paths: list[str] = []   # materialized dirs (public: callers
        #                              propagate them, e.g. as PYTHONPATH)
        self._saved_cwd: Optional[str] = None

    def __enter__(self):
        if not self.env:
            return self   # hot path: the vast majority of tasks
        container = self.env.get("container")
        if container:
            # containerized envs only apply inside a worker that was
            # LAUNCHED in that image (container_command below); a plain
            # worker can't re-root itself mid-task
            have = os.environ.get("RAY_TPU_CONTAINER_IMAGE", "")
            if have != container["image"]:
                runtime = container_runtime()
                hint = ("no container runtime (podman/docker) on this "
                        "node" if runtime is None else
                        f"this worker runs outside the image "
                        f"(in {have or 'the host'})")
                raise RuntimeError(
                    f"runtime_env container image "
                    f"{container['image']!r} unavailable: {hint} "
                    "(reference: _private/runtime_env/container.py)")
        for k, v in (self.env.get("env_vars") or {}).items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        cache_root = os.path.join("/tmp/ray_tpu", "runtime_env_cache")
        conda = self.env.get("conda")
        if conda:
            prefix = ensure_conda_env(self.client, conda)
            _check_conda_python_compat(prefix)
            sp = conda_site_packages(prefix)
            if sp:
                sys.path.insert(0, sp)
                self.paths.append(sp)
            for k, v in (("CONDA_PREFIX", prefix),
                         ("PATH", os.path.join(prefix, "bin")
                          + os.pathsep + os.environ.get("PATH", ""))):
                self._saved_env.setdefault(k, os.environ.get(k))
                os.environ[k] = v
        pip = self.env.get("pip")
        if pip:
            target = ensure_pip_env(self.client, list(pip))
            sys.path.insert(0, target)
            self.paths.append(target)
        for field, chdir in (("working_dir", True), ("py_modules", False)):
            ref = self.env.get(field)
            if not ref:
                continue
            refs = [ref] if isinstance(ref, str) else list(ref)
            for r in refs:
                if isinstance(r, str) and r.startswith("whl:"):
                    # a wheel on py_modules: extract it straight onto
                    # sys.path (a wheel is an importable zip layout —
                    # reference: py_modules.py wheel support)
                    whl = _materialize_wheel(self.client, r, cache_root)
                    path = _extract_wheel(whl, cache_root)
                elif (isinstance(r, str) and r.endswith(".whl")
                        and os.path.isfile(r)):
                    # local wheel path (single-machine / unprepared env)
                    path = _extract_wheel(r, cache_root)
                else:
                    path = (ensure_package(self.client, r)
                            if self.client is not None
                            and not os.path.isdir(r) else r)
                sys.path.insert(0, path)
                self.paths.append(path)
                if chdir and self._saved_cwd is None:
                    self._saved_cwd = os.getcwd()
                    os.chdir(path)
        return self

    def __exit__(self, *exc):
        if self.paths:
            # a reused worker must not leak env-provided modules into
            # later tasks that did NOT request this env (the reference
            # avoids this by binding workers to one env hash; here the
            # env's imports are evicted instead so workers stay shared)
            roots = tuple(os.path.abspath(p) + os.sep for p in self.paths)
            for name, mod in list(sys.modules.items()):
                origin = getattr(mod, "__file__", None)
                if origin and os.path.abspath(origin).startswith(roots):
                    del sys.modules[name]
        for p in self.paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._saved_cwd is not None:
            os.chdir(self._saved_cwd)
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False
