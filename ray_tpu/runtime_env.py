"""Runtime environments: per-job/task execution context.

Reference capability: python/ray/_private/runtime_env/ — the scoped-down
slice that matters without package installation (this environment bakes
dependencies): ``env_vars`` (applied around execution),
``working_dir`` and ``py_modules`` (zipped, content-addressed in the
cluster KV store, materialized into a worker-local cache and put on
sys.path — reference: runtime_env/working_dir.py + packaging.py).
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Optional

_MAX_PACKAGE_BYTES = 100 * 1024 * 1024
_EXCLUDE_DIRS = {".git", "__pycache__", ".venv", "node_modules",
                 ".pytest_cache", ".mypy_cache"}


def validate(runtime_env: dict) -> dict:
    known = {"env_vars", "working_dir", "py_modules"}
    unknown = set(runtime_env) - known
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; supported: "
            f"{sorted(known)} (pip/conda are out of scope: dependencies "
            "are baked into the cluster image)")
    ev = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in ev.items()):
        raise ValueError("env_vars must be str -> str")
    return runtime_env


def package_directory(path: str) -> bytes:
    """Zip a directory deterministically (reference:
    packaging.py create_package)."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for f in sorted(files):
                full = os.path.join(root, f)
                rel = os.path.relpath(full, path)
                try:
                    total += os.path.getsize(full)
                except OSError:
                    continue
                if total > _MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir exceeds "
                        f"{_MAX_PACKAGE_BYTES >> 20} MiB")
                # from_file keeps permission bits (exec scripts survive
                # extraction); the pinned date keeps the hash stable
                info = zipfile.ZipInfo.from_file(full, rel)
                info.date_time = (1980, 1, 1, 0, 0, 0)
                with open(full, "rb") as fh:
                    z.writestr(info, fh.read())
    return buf.getvalue()


def package_hash(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()[:32]


def upload_package(client, data: bytes) -> str:
    """Content-addressed upload into the cluster KV (reference:
    packaging.py upload_package_if_needed).  Returns the package hash."""
    h = package_hash(data)
    key = f"runtime_env:pkg:{h}".encode()
    if client.kv_get(key) is None:
        client.kv_put(key, data)
    return h


def ensure_package(client, pkg_hash: str,
                   cache_root: Optional[str] = None) -> str:
    """Materialize a package into the local cache; idempotent
    (reference: working_dir.py download_and_unpack_package)."""
    cache_root = cache_root or os.path.join(
        "/tmp/ray_tpu", "runtime_env_cache")
    dest = os.path.join(cache_root, pkg_hash)
    marker = os.path.join(dest, ".ready")
    if os.path.exists(marker):
        return dest
    data = client.kv_get(f"runtime_env:pkg:{pkg_hash}".encode())
    if data is None:
        raise RuntimeError(f"runtime_env package {pkg_hash} not found "
                           "in the cluster KV store")
    tmp = dest + f".tmp{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    with zipfile.ZipFile(io.BytesIO(data)) as z:
        z.extractall(tmp)
        # extractall drops permission bits — restore them so bundled
        # scripts/binaries stay executable
        for info in z.infolist():
            mode = (info.external_attr >> 16) & 0o7777
            if mode:
                try:
                    os.chmod(os.path.join(tmp, info.filename), mode)
                except OSError:
                    pass
    try:
        os.replace(tmp, dest)   # atomic against racing workers
    except OSError:
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
    open(marker, "w").close()
    return dest


class applied_env:
    """Context manager applying a runtime_env around execution
    (env_vars set/restored; working_dir/py_modules on sys.path + cwd)."""

    def __init__(self, runtime_env: Optional[dict], client=None):
        self.env = runtime_env or {}
        self.client = client
        self._saved_env: dict[str, Optional[str]] = {}
        self.paths: list[str] = []   # materialized dirs (public: callers
        #                              propagate them, e.g. as PYTHONPATH)
        self._saved_cwd: Optional[str] = None

    def __enter__(self):
        for k, v in (self.env.get("env_vars") or {}).items():
            self._saved_env[k] = os.environ.get(k)
            os.environ[k] = v
        for field, chdir in (("working_dir", True), ("py_modules", False)):
            ref = self.env.get(field)
            if not ref:
                continue
            refs = [ref] if isinstance(ref, str) else list(ref)
            for r in refs:
                path = (ensure_package(self.client, r)
                        if self.client is not None and not os.path.isdir(r)
                        else r)
                sys.path.insert(0, path)
                self.paths.append(path)
                if chdir and self._saved_cwd is None:
                    self._saved_cwd = os.getcwd()
                    os.chdir(path)
        return self

    def __exit__(self, *exc):
        for p in self.paths:
            try:
                sys.path.remove(p)
            except ValueError:
                pass
        if self._saved_cwd is not None:
            os.chdir(self._saved_cwd)
        for k, old in self._saved_env.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old
        return False
