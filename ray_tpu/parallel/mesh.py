"""Device meshes: the TPU-native resource fabric.

No reference analogue — this is the TPU design delta (SURVEY.md §7 delta 1
& 3): where the reference treats accelerators as an opaque count
(``num_gpus``), TPU scheduling is topology-first.  A ``MeshSpec`` names the
parallelism axes (dp/fsdp/tp/sp/ep/pp + a cross-slice DCN axis) and maps
them onto physical devices so XLA collectives ride ICI within a slice and
DCN across slices (cf. jax-ml.github.io/scaling-book recipe: pick a mesh,
annotate shardings, let XLA insert collectives).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Canonical axis names used across ray_tpu.train / models:
#   dp    — data parallel (batch split, gradients psum)
#   fsdp  — fully-sharded data parallel (params sharded over this axis too)
#   tp    — tensor parallel (heads / mlp sharded)
#   sp    — sequence/context parallel (ring attention over this axis)
#   ep    — expert parallel (MoE experts)
#   pp    — pipeline parallel (layer stages)
#   dcn   — cross-slice data parallel over DCN (multi-pod)
AXIS_ORDER = ("dcn", "pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclass(frozen=True)
class MeshSpec:
    """Named axis sizes; -1 on at most one axis = fill with all devices."""

    axes: dict[str, int] = field(default_factory=dict)

    def resolved(self, n_devices: int) -> dict[str, int]:
        axes = {k: v for k, v in self.axes.items() if v != 1 or k in ("dp",)}
        if not axes:
            axes = {"dp": -1}
        fills = [k for k, v in axes.items() if v == -1]
        if len(fills) > 1:
            raise ValueError(f"Only one axis may be -1, got {fills}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if fills:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            axes[fills[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"Mesh axes {axes} need {fixed} devices, have {n_devices}")
        # canonical order for predictable ICI layout
        return {k: axes[k] for k in AXIS_ORDER if k in axes} | {
            k: v for k, v in axes.items() if k not in AXIS_ORDER}


def create_mesh(axes: Optional[dict[str, int]] = None,
                devices: Optional[Sequence] = None,
                allow_split_physical_axes: bool = True) -> Mesh:
    """Build a ``jax.sharding.Mesh`` with named axes.

    ``mesh_utils.create_device_mesh`` lays devices out so that the
    innermost axes map to nearest ICI neighbors (reference capability
    being replaced: NCCL ring construction in ray.util.collective
    nccl_collective_group.py:127 — on TPU the topology mapping happens
    here, at mesh build time, and XLA emits the collectives).
    """
    devices = list(devices) if devices is not None else jax.devices()
    spec = MeshSpec(dict(axes) if axes else {"dp": -1})
    requested = math.prod(v for v in spec.axes.values() if v != -1)
    if (-1 not in spec.axes.values() and requested < len(devices)
            and len(devices) % requested == 0):
        # fewer devices asked for than exist (e.g. a dp=4 test mesh on an
        # 8-device host): use a prefix — the gang owns whole hosts, but a
        # mesh may be a sub-slice
        devices = devices[:requested]
    resolved = spec.resolved(len(devices))
    shape = tuple(resolved.values())
    try:
        dev_array = mesh_utils.create_device_mesh(
            shape, devices=devices,
            allow_split_physical_axes=allow_split_physical_axes)
    except (ValueError, AssertionError):
        dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, axis_names=tuple(resolved.keys()))


def create_hybrid_mesh(ici_axes: dict[str, int], dcn_size: int,
                       devices: Optional[Sequence] = None) -> Mesh:
    """Multi-slice mesh: `dcn` outermost over slices, ICI axes within
    (analogue of scaling DP over DCN while TP/SP stay inside a slice)."""
    devices = list(devices) if devices is not None else jax.devices()
    per_slice = len(devices) // dcn_size
    spec = MeshSpec(dict(ici_axes))
    resolved = spec.resolved(per_slice)
    try:
        dev_array = mesh_utils.create_hybrid_device_mesh(
            tuple(resolved.values()),
            dcn_mesh_shape=(dcn_size,) + (1,) * (len(resolved) - 1),
            devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape((dcn_size,)
                                                + tuple(resolved.values()))
    return Mesh(dev_array, axis_names=("dcn",) + tuple(resolved.keys()))


def mesh_shape(mesh: Mesh) -> dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def data_axes(mesh: Mesh) -> tuple[str, ...]:
    """Axes over which the global batch is split."""
    return tuple(a for a in ("dcn", "dp", "fsdp") if a in mesh.axis_names)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for [batch, ...] host data entering the mesh."""
    axes = data_axes(mesh)
    return NamedSharding(mesh, PartitionSpec(axes if axes else None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())


def local_device_count() -> int:
    return jax.local_device_count()
