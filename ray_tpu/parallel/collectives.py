"""Collective communication, two planes.

Reference analogue: ray.util.collective (python/ray/util/collective/
collective.py:120-655 — init_collective_group, allreduce:258, barrier:298,
reduce:311, broadcast:373, allgather:423, reducescatter:472, send/recv)
with NCCL/Gloo backends.

TPU-native split (SURVEY.md §5 "distributed communication backend"):
  * **Compiled plane** — collectives inside jit/shard_map lower to XLA
    ICI collectives (psum/all_gather/ppermute/reduce_scatter).  This is
    the replacement for NCCL: zero Python in the loop, fused with compute.
  * **Host plane** — out-of-band CPU collectives between *actors* through
    the object store (the Gloo analogue), for control data and CPU-only
    workers.  Rendezvous is a named actor, mirroring the reference's
    named-actor NCCL-uniqueid exchange (collective_group/util.py).
"""

from __future__ import annotations

import time
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec
from ray_tpu.parallel.jax_compat import shard_map

# ---------------------------------------------------------------------------
# compiled plane — use inside shard_map'd / pjit'd functions

REDUCE_OPS = ("sum", "mean", "max", "min", "prod")


def allreduce(x, axis_name: str, op: str = "sum"):
    """In-program allreduce (reference: collective.py:258 allreduce)."""
    if op == "sum":
        return jax.lax.psum(x, axis_name)
    if op == "mean":
        return jax.lax.pmean(x, axis_name)
    if op == "max":
        return jax.lax.pmax(x, axis_name)
    if op == "min":
        return jax.lax.pmin(x, axis_name)
    if op == "prod":
        return jnp.exp(jax.lax.psum(jnp.log(x), axis_name))
    raise ValueError(f"op must be one of {REDUCE_OPS}")


def allgather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reducescatter(x, axis_name: str, axis: int = 0):
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=axis,
                                tiled=True)


def broadcast(x, axis_name: str, root: int = 0):
    """Every shard gets root's value."""
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def permute(x, axis_name: str, perm: list[tuple[int, int]]):
    """Point-to-point ring shift (reference: send/recv collective.py:531,594
    — on TPU p2p is a compiled ppermute over ICI)."""
    return jax.lax.ppermute(x, axis_name, perm)


def ring_perm(n: int, shift: int = 1) -> list[tuple[int, int]]:
    return [(i, (i + shift) % n) for i in range(n)]


def axis_index(axis_name: str):
    return jax.lax.axis_index(axis_name)


def shard_fn(mesh: Mesh, in_specs, out_specs, fn=None, check_vma: bool = False):
    """Decorator sugar over shard_map for writing collective code."""
    def wrap(f):
        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=check_vma)
    return wrap(fn) if fn is not None else wrap


# ---------------------------------------------------------------------------
# host plane — out-of-band collectives between actors


class _Rendezvous:
    """Named-actor blackboard for a collective group (reference analogue:
    rendezvous via named actor storing the NCCL unique id,
    python/ray/util/collective/collective_group/util.py)."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.epochs: dict[tuple[str, int], dict[int, Any]] = {}

    def put(self, key: str, epoch: int, rank: int, value) -> int:
        slot = self.epochs.setdefault((key, epoch), {"vals": {}, "seen": set()})
        slot["vals"][rank] = value
        return len(slot["vals"])

    def collect(self, key: str, epoch: int, ranks: list[int], rank: int):
        slot = self.epochs.get((key, epoch))
        if slot is None or any(r not in slot["vals"] for r in ranks):
            return None
        out = {r: slot["vals"][r] for r in ranks}
        # server-side gc once every participant has collected — no client
        # can race a deletion it hasn't consumed yet
        slot["seen"].add(rank)
        if slot["seen"] >= set(ranks):
            del self.epochs[(key, epoch)]
        return out


def create_collective_group(name: str, world_size: int):
    """Create the group's rendezvous actor (call once, any process).
    Reference: collective.py:151 create_collective_group."""
    import ray_tpu
    from ray_tpu.core.actor import ActorClass
    cls = ActorClass(_Rendezvous, name=f"rt_collective::{name}",
                     get_if_exists=True)
    return cls.remote(world_size)


class CollectiveGroup:
    """Per-process handle; rank is explicit (reference:
    init_collective_group collective.py:120)."""

    def __init__(self, name: str, world_size: int, rank: int,
                 poll_interval: float = 0.002):
        import ray_tpu
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._poll = poll_interval
        # per-key epochs: ranks doing the same sequence of ops on a key
        # stay aligned even when other keys are used by subsets (p2p)
        self._epochs: dict[str, int] = {}
        try:
            self._board = ray_tpu.get_actor(f"rt_collective::{name}")
        except Exception:
            self._board = create_collective_group(name, world_size)

    # -- internals --------------------------------------------------------

    def _exchange(self, key: str, value, ranks: Optional[list[int]] = None):
        import ray_tpu
        ranks = ranks if ranks is not None else list(range(self.world_size))
        epoch = self._epochs.get(key, 0)
        self._epochs[key] = epoch + 1
        ray_tpu.get(self._board.put.remote(key, epoch, self.rank, value))
        deadline = time.time() + 120
        while True:
            vals = ray_tpu.get(self._board.collect.remote(key, epoch, ranks,
                                                          self.rank))
            if vals is not None:
                return vals
            if time.time() > deadline:
                raise TimeoutError(
                    f"collective '{key}' timed out at rank {self.rank}")
            time.sleep(self._poll)

    # -- API (mirrors collective.py surface) ------------------------------

    def barrier(self) -> None:
        self._exchange("barrier", None)

    def allreduce(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        vals = self._exchange("allreduce", np.asarray(x))
        stack = np.stack([vals[r] for r in sorted(vals)])
        if op == "sum":
            return stack.sum(0)
        if op == "mean":
            return stack.mean(0)
        if op == "max":
            return stack.max(0)
        if op == "min":
            return stack.min(0)
        raise ValueError(f"op must be one of {REDUCE_OPS}")

    def allgather(self, x: np.ndarray) -> list[np.ndarray]:
        vals = self._exchange("allgather", np.asarray(x))
        return [np.asarray(vals[r]) for r in sorted(vals)]

    def broadcast(self, x: Optional[np.ndarray], root: int = 0) -> np.ndarray:
        vals = self._exchange("broadcast",
                              np.asarray(x) if self.rank == root else None)
        return np.asarray(vals[root])

    def reduce(self, x: np.ndarray, root: int = 0,
               op: str = "sum") -> Optional[np.ndarray]:
        out = self.allreduce(x, op=op)
        return out if self.rank == root else None

    def reducescatter(self, x: np.ndarray, op: str = "sum") -> np.ndarray:
        full = self.allreduce(x, op=op)
        chunks = np.array_split(full, self.world_size, axis=0)
        return chunks[self.rank]

    def send(self, x: np.ndarray, dst: int) -> None:
        self._exchange(f"p2p:{self.rank}->{dst}", np.asarray(x),
                       ranks=[self.rank, dst] if dst != self.rank
                       else [self.rank])

    def recv(self, src: int) -> np.ndarray:
        vals = self._exchange(f"p2p:{src}->{self.rank}", None,
                              ranks=[src, self.rank] if src != self.rank
                              else [self.rank])
        return np.asarray(vals[src])
