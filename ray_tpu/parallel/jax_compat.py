"""Version-compat shims for jax APIs with moved/renamed surfaces.

The toolchain image pins an older jax where ``shard_map`` lives in
``jax.experimental.shard_map``, its replication check is spelled
``check_rep`` (newer: top-level ``jax.shard_map`` with ``check_vma``),
and partial-manual meshes use ``auto=`` (newer: ``axis_names=``).
Callers write the NEW spelling and import from here; the shim
translates downward when running on the older jax.
"""

from __future__ import annotations

import inspect

try:                                    # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map
except ImportError:                     # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f=None, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    if "axis_names" in kw and "axis_names" not in _PARAMS:
        # old spelling is the complement: `auto` lists the mesh axes
        # shard_map must NOT bind manually
        axis_names = kw.pop("axis_names")
        mesh = kw.get("mesh")
        auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                if mesh is not None else frozenset())
        if auto:
            if "auto" not in _PARAMS:
                # dropping the restriction would silently bind every
                # mesh axis manually — wrong collectives, not an error
                raise NotImplementedError(
                    "this jax's shard_map supports neither axis_names "
                    "nor auto; partial-manual meshes are unavailable")
            kw["auto"] = auto
    if f is None:
        return lambda g: shard_map(g, **kw)
    return _shard_map(f, **kw)
