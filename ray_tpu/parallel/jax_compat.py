"""Version-compat shims for jax APIs with moved/renamed surfaces.

The toolchain image pins an older jax where ``shard_map`` lives in
``jax.experimental.shard_map``, its replication check is spelled
``check_rep`` (newer: top-level ``jax.shard_map`` with ``check_vma``),
and partial-manual meshes use ``auto=`` (newer: ``axis_names=``).
Callers write the NEW spelling and import from here; the shim
translates downward when running on the older jax.
"""

from __future__ import annotations

import inspect

try:                                    # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map
except ImportError:                     # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f=None, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    if "axis_names" in kw and "axis_names" not in _PARAMS:
        # old spelling is the complement: `auto` lists the mesh axes
        # shard_map must NOT bind manually
        axis_names = kw.pop("axis_names")
        mesh = kw.get("mesh")
        auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                if mesh is not None else frozenset())
        if auto:
            if "auto" not in _PARAMS:
                # dropping the restriction would silently bind every
                # mesh axis manually — wrong collectives, not an error
                raise NotImplementedError(
                    "this jax's shard_map supports neither axis_names "
                    "nor auto; partial-manual meshes are unavailable")
            kw["auto"] = auto
    if f is None:
        return lambda g: shard_map(g, **kw)
    return _shard_map(f, **kw)


# ---------------------------------------------------------------------------
# elastic jax.distributed (parallel/gang.py)
#
# Three version-gated capabilities the elastic gang needs that the public
# jax.distributed surface doesn't expose:
#
#   * SURVIVABLE membership: the stock DistributedRuntimeClient's
#     missed-heartbeat/error-poll callback LOG(FATAL)s the process the
#     moment ANY peer dies — the exact opposite of shrink-and-resume.
#     ``distributed_initialize(resilient=True)`` builds the client with a
#     no-op callback and ``shutdown_on_destruction=False`` so member
#     death is an ERROR the gang layer handles, not process suicide.
#   * FAST detection: heartbeat interval/threshold knobs (seconds, not
#     the stock ~100 s window) so a dead member poisons collectives
#     quickly and reform isn't hostage to a long timeout.
#   * ABANDON: ``distributed_abandon()`` force-leaves a (possibly
#     poisoned) world without the collective shutdown barrier — the
#     barrier can never complete once a peer is dead — then
#     ``clear_backends()`` drops the cached global-device view so the
#     next initialize sees the NEW world.


def distributed_initialize(coordinator_address: str, num_processes: int,
                           process_id: int, *, resilient: bool = True,
                           heartbeat_interval_s: int = 1,
                           max_missing_heartbeats: int = 5,
                           init_timeout_s: int = 120) -> str:
    """Initialize jax.distributed; returns "resilient" when the
    peer-death-survivable client was installed, "plain" when this jax's
    private surface moved and we fell back to the public API (elastic
    shrink then degrades to full-restart recovery)."""
    import jax
    if not resilient:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return "plain"
    try:
        from jax._src import distributed
        from jax._src.lib import xla_extension
        st = distributed.global_state
        if st.client is not None:
            raise RuntimeError("jax.distributed already initialized")
        port = coordinator_address.rsplit(":", 1)[1]
        if process_id == 0:
            st.service = xla_extension.get_distributed_runtime_service(
                "[::]:" + port, num_processes,
                heartbeat_interval=heartbeat_interval_s,
                max_missing_heartbeats=max_missing_heartbeats)
        client = xla_extension.get_distributed_runtime_client(
            coordinator_address, process_id,
            init_timeout=init_timeout_s, shutdown_timeout=5,
            heartbeat_interval=heartbeat_interval_s,
            max_missing_heartbeats=max_missing_heartbeats,
            missed_heartbeat_callback=lambda *a, **k: None,
            shutdown_on_destruction=False, use_compression=True)
        client.connect()
        st.client = client
        st.process_id = process_id
        st.num_processes = num_processes
        st.coordinator_address = coordinator_address
        return "resilient"
    except (ImportError, AttributeError, TypeError):
        # moved private surface: correctness over elasticity.  A
        # partially-built resilient setup (e.g. the service came up but
        # the client factory's signature changed) must be torn down
        # first, or the public-API fallback re-binds the same port.
        try:
            from jax._src import distributed as _dist
            st = _dist.global_state
            for attr in ("client", "service"):
                obj = getattr(st, attr, None)
                if obj is not None:
                    setattr(st, attr, None)
                    try:
                        obj.shutdown()
                    except Exception:
                        pass
        except ImportError:
            pass
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return "plain"


def distributed_abandon(timeout_s: float = 20.0) -> None:
    """Leave the current jax.distributed world WITHOUT requiring the
    collective shutdown barrier to succeed (it can't once a member is
    dead).  The barrier attempt runs on a bounded side thread: with the
    dead peer already marked by the coordination service it fails fast;
    a wedged one is abandoned to the daemon thread."""
    import threading

    try:
        from jax._src import distributed
        st = distributed.global_state
    except ImportError:
        import jax
        jax.distributed.shutdown()
        return
    client, service = st.client, st.service
    st.client = None
    st.service = None
    st.preemption_sync_manager = None
    st.process_id = None
    st.num_processes = None
    st.coordinator_address = None

    def quiet_shutdown(obj):
        try:
            obj.shutdown()
        except Exception:
            pass

    for obj in (client, service):
        if obj is None:
            continue
        t = threading.Thread(target=quiet_shutdown, args=(obj,),
                             daemon=True)
        t.start()
        t.join(timeout=timeout_s)


def clear_backends() -> None:
    """Drop cached XLA backends (and with them the stale global-device
    view) so the next backend touch re-initializes against the CURRENT
    jax.distributed world."""
    import jax
    f = getattr(jax, "clear_backends", None)
    if f is None:
        from jax.extend import backend as _xb
        f = _xb.clear_backends
    f()


def enable_cpu_gloo_collectives() -> None:
    """Make CPU-backend cross-process collectives real (the multi-host
    test shape): newer jax spells it jax_cpu_collectives_implementation,
    older jax_cpu_enable_gloo_collectives.  Must run before the CPU
    backend initializes."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        try:
            jax.config.update("jax_cpu_enable_gloo_collectives", True)
        except (AttributeError, ValueError):
            pass   # very old jax: single-host only
