"""Version-compat shims for jax APIs with moved/renamed surfaces.

The toolchain image pins an older jax where ``shard_map`` lives in
``jax.experimental.shard_map``, its replication check is spelled
``check_rep`` (newer: top-level ``jax.shard_map`` with ``check_vma``),
and partial-manual meshes use ``auto=`` (newer: ``axis_names=``).
Callers write the NEW spelling and import from here; the shim
translates downward when running on the older jax.
"""

from __future__ import annotations

import inspect

try:                                    # jax >= 0.6 top-level export
    from jax import shard_map as _shard_map
except ImportError:                     # older jax: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f=None, **kw):
    if "check_vma" in kw and "check_vma" not in _PARAMS:
        kw["check_rep"] = kw.pop("check_vma")
    if "axis_names" in kw and "axis_names" not in _PARAMS:
        # old spelling is the complement: `auto` lists the mesh axes
        # shard_map must NOT bind manually
        axis_names = kw.pop("axis_names")
        mesh = kw.get("mesh")
        auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
                if mesh is not None else frozenset())
        if auto:
            if "auto" not in _PARAMS:
                # dropping the restriction would silently bind every
                # mesh axis manually — wrong collectives, not an error
                raise NotImplementedError(
                    "this jax's shard_map supports neither axis_names "
                    "nor auto; partial-manual meshes are unavailable")
            kw["auto"] = auto
    if f is None:
        return lambda g: shard_map(g, **kw)
    return _shard_map(f, **kw)


# ---------------------------------------------------------------------------
# elastic jax.distributed (parallel/gang.py)
#
# Three version-gated capabilities the elastic gang needs that the public
# jax.distributed surface doesn't expose:
#
#   * SURVIVABLE membership: the stock DistributedRuntimeClient's
#     missed-heartbeat/error-poll callback LOG(FATAL)s the process the
#     moment ANY peer dies — the exact opposite of shrink-and-resume.
#     ``distributed_initialize(resilient=True)`` builds the client with a
#     no-op callback and ``shutdown_on_destruction=False`` so member
#     death is an ERROR the gang layer handles, not process suicide.
#     The coordination service must additionally never DECLARE a member
#     dead: this XLA propagates "unhealthy task" findings to every
#     surviving client through error polling, and the agent's polling
#     thread terminates the process (uncatchable std::bad_cast inside
#     the C++->Python callback hop) when it hands the error over — so
#     heartbeat-miss detection is effectively disabled on both sides
#     (``max_missing_heartbeats`` ~ 10^7) and membership health belongs
#     to the gang layer alone (actor death watch + ping probes; a dead
#     peer still poisons in-flight collectives via gloo's own TCP
#     errors, which surface as ordinary Python exceptions).
#   * ABANDON: ``distributed_abandon()`` force-leaves a (possibly
#     poisoned) world.  It must not attempt ANY shutdown handshake:
#     the collective shutdown barrier can never complete once a peer is
#     dead, and its timeout error would be propagated to the surviving
#     clients' polling threads — the same process-killing path as
#     above.  The old client/service are instead parked in a
#     module-level list (a deliberate, bounded leak: one pair per
#     re-gang) so not even a destructor runs against the old world;
#     ``clear_backends()`` then drops the cached global-device view so
#     the next initialize sees the NEW world.


def distributed_initialize(coordinator_address: str, num_processes: int,
                           process_id: int, *, resilient: bool = True,
                           heartbeat_interval_s: int = 1,
                           max_missing_heartbeats: int = 10_000_000,
                           init_timeout_s: int = 120) -> str:
    """Initialize jax.distributed; returns "resilient" when the
    peer-death-survivable client was installed, "plain" when this jax's
    private surface moved and we fell back to the public API (elastic
    shrink then degrades to full-restart recovery)."""
    import jax
    if not resilient:
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return "plain"
    try:
        from jax._src import distributed
        from jax._src.lib import xla_extension
        st = distributed.global_state
        if st.client is not None:
            raise RuntimeError("jax.distributed already initialized")
        port = coordinator_address.rsplit(":", 1)[1]
        if process_id == 0:
            st.service = xla_extension.get_distributed_runtime_service(
                "[::]:" + port, num_processes,
                heartbeat_interval=heartbeat_interval_s,
                max_missing_heartbeats=max_missing_heartbeats)
        client = xla_extension.get_distributed_runtime_client(
            coordinator_address, process_id,
            init_timeout=init_timeout_s, shutdown_timeout=5,
            heartbeat_interval=heartbeat_interval_s,
            max_missing_heartbeats=max_missing_heartbeats,
            missed_heartbeat_callback=lambda *a, **k: None,
            shutdown_on_destruction=False, use_compression=True)
        client.connect()
        st.client = client
        st.process_id = process_id
        st.num_processes = num_processes
        st.coordinator_address = coordinator_address
        return "resilient"
    except (ImportError, AttributeError, TypeError):
        # moved private surface: correctness over elasticity.  A
        # partially-built resilient setup (e.g. the service came up but
        # the client factory's signature changed) must be torn down
        # first, or the public-API fallback re-binds the same port.
        try:
            from jax._src import distributed as _dist
            st = _dist.global_state
            for attr in ("client", "service"):
                obj = getattr(st, attr, None)
                if obj is not None:
                    setattr(st, attr, None)
                    try:
                        obj.shutdown()
                    except Exception:
                        pass
        except ImportError:
            pass
        jax.distributed.initialize(coordinator_address=coordinator_address,
                                   num_processes=num_processes,
                                   process_id=process_id)
        return "plain"


# worlds left behind by distributed_abandon().  Holding the references
# forever is the point: calling .shutdown() on either object — or even
# letting its destructor run — talks to a world with a dead member, and
# the resulting barrier-timeout error comes back through the surviving
# clients' error-polling threads as process termination (see the module
# comment above).  One (client, service) pair leaks per re-gang; the
# old client keeps heartbeating the old service quietly, generating no
# errors, until the process exits.
_abandoned_worlds: list = []


def distributed_abandon(timeout_s: float = 20.0) -> None:
    """Leave the current jax.distributed world WITHOUT any shutdown
    handshake: the collective shutdown barrier can never complete once
    a peer is dead, and even ATTEMPTING it propagates a timeout error
    that kills the surviving peers' polling threads.  The old
    client/service pair is parked (never shut down, never destroyed) so
    the old world stays silent; the global_state slots are cleared so
    the next distributed_initialize builds a fresh world."""
    try:
        from jax._src import distributed
        st = distributed.global_state
    except ImportError:
        import jax
        jax.distributed.shutdown()
        return
    if st.client is not None or st.service is not None:
        _abandoned_worlds.append((st.client, st.service))
    st.client = None
    st.service = None
    st.preemption_sync_manager = None
    st.process_id = None
    st.num_processes = None
    st.coordinator_address = None


def clear_backends() -> None:
    """Drop cached XLA backends (and with them the stale global-device
    view) so the next backend touch re-initializes against the CURRENT
    jax.distributed world."""
    import jax
    f = getattr(jax, "clear_backends", None)
    if f is None:
        from jax.extend import backend as _xb
        f = _xb.clear_backends
    f()


def enable_cpu_gloo_collectives() -> None:
    """Make CPU-backend cross-process collectives real (the multi-host
    test shape): newer jax spells it jax_cpu_collectives_implementation,
    older jax_cpu_enable_gloo_collectives.  Must run before the CPU
    backend initializes."""
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except (AttributeError, ValueError):
        try:
            jax.config.update("jax_cpu_enable_gloo_collectives", True)
        except (AttributeError, ValueError):
            pass   # very old jax: single-host only
