"""Pipeline parallelism: GPipe-style microbatch schedule over the ``pp``
mesh axis.

TPU-native design (green-field — the reference has no pipeline engine;
SURVEY.md §2.4 makes PP a first-class axis requirement): the layer stack
is sharded over ``pp`` (each stage holds a contiguous block of layers),
the batch is split into M microbatches, and one compiled ``lax.scan``
runs T = M + S - 1 ticks.  Each tick every stage applies its layer block
to its resident microbatch, then hands the activation to the next stage
with a single-hop ``ppermute`` riding the ICI ring.  Reverse-mode AD
through the scan + ppermute yields the mirrored backward pipeline
automatically — fill/drain bubble fraction (S-1)/(T), so more
microbatches amortize it.

The stage loop runs under ``shard_map`` manual ONLY over ``pp``
(``axis_names={"pp"}``): dp/fsdp/tp axes stay in GSPMD auto mode, so the
per-stage compute keeps its usual logical-axis sharding constraints and
XLA still inserts the tensor-parallel collectives inside each stage.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.jax_compat import shard_map


def num_stages(mesh: Mesh) -> int:
    return mesh.shape.get("pp", 1)


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   x_mb: jax.Array, stage_params: Any, *,
                   mesh: Mesh, axis: str = "pp",
                   carry_aux: bool = False):
    """Run ``stage_fn`` as an S-stage pipeline over microbatched inputs.

    Args:
      stage_fn: ``(local_stage_params, x) -> x`` — applies ONE stage's
        layer block; input/output shapes must match (residual stream).
        With ``carry_aux``: ``(lp, x, aux) -> (x, aux)`` where ``aux``
        is a scalar accumulated ACROSS stages (it rides the same
        ppermute hand-off as the activation — the MoE load-balance loss
        for MoE+pp composition).
      x_mb: ``[M, mb, ...]`` microbatched activations, replicated over
        ``axis`` (other mesh axes stay auto-sharded).
      stage_params: pytree whose leaves have a leading layers dim
        divisible by the stage count; sharded over ``axis`` on dim 0.
      mesh: mesh containing ``axis``.

    Returns ``[M, mb, ...]`` final-stage outputs, plus (with
    ``carry_aux``) the summed aux scalar over all microbatches+stages.
    """
    S = mesh.shape[axis]
    M = x_mb.shape[0]
    if S == 1:
        return _single_stage(stage_fn, x_mb, stage_params,
                             carry_aux=carry_aux)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def body(x_mb, lp):
        r = lax.axis_index(axis)

        def tick(carry, t):
            state, aux_state, outs, aux_done = carry
            mbi = jnp.clip(t, 0, M - 1)
            fresh = x_mb[mbi]
            # stage 0 injects a fresh microbatch; later stages consume
            # the activation handed over by the previous stage last tick
            x = jnp.where(r == 0, fresh, state)
            aux_in = jnp.where(r == 0, 0.0, aux_state)
            if carry_aux:
                x, aux_in = stage_fn(lp, x, aux_in)
            else:
                x = stage_fn(lp, x)
            li = t - (S - 1)
            ci = jnp.clip(li, 0, M - 1)
            valid = li >= 0  # li < M always holds: t <= M+S-2
            outs = outs.at[ci].set(jnp.where(valid, x, outs[ci]))
            # the LAST stage banks each microbatch's completed aux sum
            aux_done = aux_done + jnp.where(
                valid & (r == S - 1), aux_in, 0.0)
            state = lax.ppermute(x, axis, perm)
            aux_state = lax.ppermute(aux_in, axis, perm)
            return (state, aux_state, outs, aux_done), None

        state0 = jnp.zeros_like(x_mb[0])
        outs0 = jnp.zeros_like(x_mb)
        (_, _, outs, aux_done), _ = lax.scan(
            tick, (state0, jnp.zeros(()), outs0, jnp.zeros(())),
            jnp.arange(M + S - 1))
        # per-stage buffers stack over pp; only the last stage's slice
        # holds final-layer activations — the caller reads [-1].  The
        # aux total lives on the last stage; psum replicates it.
        aux_total = lax.psum(aux_done, axis)
        return outs[None], aux_total[None]

    in_specs = (P(), jax.tree.map(lambda _: P(axis), stage_params))
    staged, aux = shard_map(body, mesh=mesh, in_specs=in_specs,
                            out_specs=(P(axis), P(axis)),
                            axis_names={axis},
                            check_vma=False)(x_mb, stage_params)
    if carry_aux:
        return staged[-1], aux[0]
    return staged[-1]


def _single_stage(stage_fn, x_mb, stage_params, carry_aux=False):
    """Degenerate pp=1 path: plain scan over microbatches."""
    if carry_aux:
        def mb_step(acc, x):
            y, a = stage_fn(stage_params, x, jnp.zeros(()))
            return acc + a, y
        aux, outs = lax.scan(mb_step, jnp.zeros(()), x_mb)
        return outs, aux

    def mb_step(_, x):
        return None, stage_fn(stage_params, x)
    _, outs = lax.scan(mb_step, None, x_mb)
    return outs
