"""TPU-native parallelism: meshes, shardings, collectives, gangs,
ring attention, pipeline parallelism."""

from ray_tpu.parallel.mesh import (MeshSpec, create_mesh, create_hybrid_mesh,
                                   mesh_shape, data_axes, batch_sharding,
                                   replicated)
from ray_tpu.parallel.sharding import (DEFAULT_LLM_RULES, spec_for,
                                       sharding_for, tree_shardings,
                                       constrain)
from ray_tpu.parallel import collectives
from ray_tpu.parallel.gang import TpuGang, GangConfig, form_gang

__all__ = [
    "MeshSpec", "create_mesh", "create_hybrid_mesh", "mesh_shape",
    "data_axes", "batch_sharding", "replicated", "DEFAULT_LLM_RULES",
    "spec_for", "sharding_for", "tree_shardings", "constrain",
    "collectives", "TpuGang", "GangConfig", "form_gang",
]
