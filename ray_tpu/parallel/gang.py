"""Gang: slice-aware SPMD worker group.

The TPU-defining layer (SURVEY.md §7 M3).  Replaces the reference's
WorkerGroup + out-of-band NCCL rendezvous (reference:
train/_internal/worker_group.py:92 + train/torch/config.py:69
_setup_torch_process_group) with slice-native formation:

  * single host (this round's fast path): ONE in-process member owns all
    local chips — jax is single-controller per host, so the driver itself
    drives the mesh; no process hop, no serialization of arrays.
  * multi host: one member process per TPU host, co-initialized with
    ``jax.distributed.initialize`` (coordinator = rank-0 member), each
    running the same compiled program (SPMD).  Members are actors with
    ``num_tpus`` resources so the scheduler places them on TPU hosts.

The gang is the unit of fault tolerance: a member death breaks the ICI
mesh, so recovery = rebuild the gang and restore from checkpoint
(reference restart-based analogue: backend_executor.py:571 _restart).
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh

from ray_tpu.parallel.mesh import batch_sharding, create_mesh, mesh_shape


@dataclass
class GangConfig:
    mesh_axes: dict[str, int] = field(default_factory=lambda: {"dp": -1})
    num_hosts: int = 1
    use_cpu_devices: bool = False  # tests: virtual CPU mesh


class TpuGang:
    """Handle to a formed gang.  `run(fn, *args)` executes `fn` inside the
    mesh context on every member (single-host: inline)."""

    def __init__(self, config: Optional[GangConfig] = None,
                 devices: Optional[list] = None):
        self.config = config or GangConfig()
        if devices is None:
            devices = (jax.devices("cpu") if self.config.use_cpu_devices
                       else jax.devices())
        self.devices = devices
        self.mesh: Mesh = create_mesh(self.config.mesh_axes, devices=devices)
        self.num_hosts = self.config.num_hosts

    # -- info -------------------------------------------------------------

    @property
    def axis_sizes(self) -> dict[str, int]:
        return mesh_shape(self.mesh)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # -- execution ---------------------------------------------------------

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Execute fn with the gang mesh active (single-host inline)."""
        with self.mesh:
            return fn(*args, **kwargs)

    def put_batch(self, batch: Any) -> Any:
        """Host batch pytree -> sharded jax.Arrays over the data axes."""
        sh = batch_sharding(self.mesh)
        return jax.tree.map(lambda x: jax.device_put(x, sh), batch)

    def shutdown(self) -> None:
        pass


def form_gang(mesh_axes: Optional[dict[str, int]] = None,
              use_cpu_devices: bool = False) -> TpuGang:
    return TpuGang(GangConfig(mesh_axes=mesh_axes or {"dp": -1},
                              use_cpu_devices=use_cpu_devices))


# ---------------------------------------------------------------------------
# multi-host formation (skeleton — exercised via dryrun in round 1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _routable_ip() -> str:
    """This host's address as seen by peers (UDP-connect trick; falls
    back to loopback on isolated machines)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class GangMember:
    """Actor body for one host's member process (multi-host path).

    Placed with ``num_tpus=<chips per host>`` so the scheduler reserves a
    whole host's chips; rank 0's address is the jax.distributed
    coordinator (the analogue of the reference's TCP-store rendezvous on
    the rank-0 train worker, train/torch/config.py:69).  With
    ``cpu_backend`` the member pins jax to N virtual CPU devices before
    backend init — the multi-host test shape (collectives ride Gloo).
    """

    def __init__(self, rank: int, world: int,
                 cpu_backend: bool = False, local_device_count: int = 0):
        self.rank = rank
        self.world = world
        self.cpu_backend = cpu_backend
        self.local_device_count = local_device_count
        self._initialized = False

    def choose_coordinator(self) -> str:
        """Rank 0 picks the rendezvous address ON ITS OWN HOST (the
        driver's loopback would be unreachable from other nodes)."""
        ip = _routable_ip()
        return f"{ip}:{_free_port()}"

    def setup(self, coordinator: str) -> dict:
        import jax as _jax
        if self.cpu_backend:
            # must land before first backend touch in this fresh process
            _jax.config.update("jax_platforms", "cpu")
            if self.local_device_count:
                try:
                    _jax.config.update("jax_num_cpu_devices",
                                       self.local_device_count)
                except AttributeError:
                    # pre-0.5 jax spelling; same pre-backend-init timing
                    import os as _os
                    _os.environ["XLA_FLAGS"] = (
                        _os.environ.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count="
                        + str(self.local_device_count))
        if self.world > 1 and not self._initialized:
            _jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=self.world, process_id=self.rank)
            self._initialized = True
        return {"rank": self.rank,
                "global_devices": len(_jax.devices()),
                "local_devices": len(_jax.local_devices()),
                "pid": __import__("os").getpid()}

    def run(self, pickled_fn: bytes, *args):
        import cloudpickle
        fn = cloudpickle.loads(pickled_fn)
        return fn(self.rank, *args)

    def pid(self) -> int:
        import os
        return os.getpid()


class MultiHostGang:
    """A formed multi-host gang: one GangMember actor per host, jointly
    initialized through jax.distributed (SPMD across processes).

    The reference analogue is the worker-group half of BackendExecutor
    (reference: train/_internal/backend_executor.py:94 start +
    worker_group.py:92); formation here is one collective
    jax.distributed.initialize instead of a framework process-group
    bootstrap.  A member death breaks the gang; recovery is re-forming a
    NEW gang (fresh coordinator, fresh processes) and restoring state
    from a checkpoint (reference: backend_executor.py:571 restart).
    """

    def __init__(self, num_members: int, *, num_tpus_per_member: float = 0,
                 cpu_backend: bool = False, devices_per_member: int = 0,
                 resources_per_member: Optional[dict] = None,
                 setup_timeout: float = 120.0):
        import ray_tpu

        self.num_members = num_members
        opts: dict = {}
        if num_tpus_per_member:
            opts["num_tpus"] = num_tpus_per_member
        if resources_per_member:
            opts["resources"] = resources_per_member
        member_cls = ray_tpu.remote(GangMember)
        if opts:
            member_cls = member_cls.options(**opts)
        self.members = [
            member_cls.remote(rank=i, world=num_members,
                              cpu_backend=cpu_backend,
                              local_device_count=devices_per_member)
            for i in range(num_members)]
        # rank 0 picks the rendezvous address on ITS host (it may be
        # scheduled on any node), then setup is a collective barrier:
        # all members must be in flight together
        self.coordinator = ray_tpu.get(
            self.members[0].choose_coordinator.remote(),
            timeout=setup_timeout)
        self.infos = ray_tpu.get(
            [m.setup.remote(self.coordinator) for m in self.members],
            timeout=setup_timeout)
        self.global_devices = self.infos[0]["global_devices"]

    def run(self, fn: Callable, *args,
            timeout: Optional[float] = None) -> list:
        """Run ``fn(rank, *args)`` on every member; returns per-rank
        results (SPMD: all ranks execute the same program).  No default
        timeout: a member-side attempt may legitimately run for hours —
        member death still fails the get with an actor-death error."""
        import cloudpickle
        import ray_tpu
        payload = cloudpickle.dumps(fn)
        refs = [m.run.remote(payload, *args) for m in self.members]
        return ray_tpu.get(refs, timeout=timeout)

    def member_pids(self) -> list[int]:
        import ray_tpu
        return ray_tpu.get([m.pid.remote() for m in self.members],
                           timeout=60)

    def shutdown(self) -> None:
        import ray_tpu
        for m in self.members:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass
