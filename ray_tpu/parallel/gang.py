"""Gang: slice-aware SPMD worker group.

The TPU-defining layer (SURVEY.md §7 M3).  Replaces the reference's
WorkerGroup + out-of-band NCCL rendezvous (reference:
train/_internal/worker_group.py:92 + train/torch/config.py:69
_setup_torch_process_group) with slice-native formation:

  * single host (this round's fast path): ONE in-process member owns all
    local chips — jax is single-controller per host, so the driver itself
    drives the mesh; no process hop, no serialization of arrays.
  * multi host: one member process per TPU host, co-initialized with
    ``jax.distributed.initialize`` (coordinator = rank-0 member), each
    running the same compiled program (SPMD).  Members are actors with
    ``num_tpus`` resources so the scheduler places them on TPU hosts.

The gang is the unit of fault tolerance: a member death breaks the ICI
mesh, so recovery = rebuild the gang and restore from checkpoint
(reference restart-based analogue: backend_executor.py:571 _restart).
"""

from __future__ import annotations

import socket
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh

from ray_tpu.parallel.mesh import batch_sharding, create_mesh, mesh_shape


@dataclass
class GangConfig:
    mesh_axes: dict[str, int] = field(default_factory=lambda: {"dp": -1})
    num_hosts: int = 1
    use_cpu_devices: bool = False  # tests: virtual CPU mesh


class TpuGang:
    """Handle to a formed gang.  `run(fn, *args)` executes `fn` inside the
    mesh context on every member (single-host: inline)."""

    def __init__(self, config: Optional[GangConfig] = None,
                 devices: Optional[list] = None):
        self.config = config or GangConfig()
        if devices is None:
            devices = (jax.devices("cpu") if self.config.use_cpu_devices
                       else jax.devices())
        self.devices = devices
        self.mesh: Mesh = create_mesh(self.config.mesh_axes, devices=devices)
        self.num_hosts = self.config.num_hosts

    # -- info -------------------------------------------------------------

    @property
    def axis_sizes(self) -> dict[str, int]:
        return mesh_shape(self.mesh)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # -- execution ---------------------------------------------------------

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Execute fn with the gang mesh active (single-host inline)."""
        with self.mesh:
            return fn(*args, **kwargs)

    def put_batch(self, batch: Any) -> Any:
        """Host batch pytree -> sharded jax.Arrays over the data axes."""
        sh = batch_sharding(self.mesh)
        return jax.tree.map(lambda x: jax.device_put(x, sh), batch)

    def shutdown(self) -> None:
        pass


def form_gang(mesh_axes: Optional[dict[str, int]] = None,
              use_cpu_devices: bool = False) -> TpuGang:
    return TpuGang(GangConfig(mesh_axes=mesh_axes or {"dp": -1},
                              use_cpu_devices=use_cpu_devices))


# ---------------------------------------------------------------------------
# multi-host formation (skeleton — exercised via dryrun in round 1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class GangMember:
    """Actor body for one host's member process (multi-host path).

    Placed with ``num_tpus=<chips per host>`` so the scheduler reserves a
    whole host's chips; rank 0's address is the jax.distributed
    coordinator (the analogue of the reference's TCP-store rendezvous on
    the rank-0 train worker, train/torch/config.py:69).
    """

    def __init__(self, rank: int, world: int, coordinator: str):
        self.rank = rank
        self.world = world
        self.coordinator = coordinator
        self._initialized = False

    def setup(self) -> str:
        import jax as _jax
        if self.world > 1 and not self._initialized:
            _jax.distributed.initialize(
                coordinator_address=self.coordinator,
                num_processes=self.world, process_id=self.rank)
            self._initialized = True
        return f"rank{self.rank}: {len(_jax.devices())} global devices"

    def run(self, pickled_fn: bytes, *args):
        import cloudpickle
        fn = cloudpickle.loads(pickled_fn)
        return fn(self.rank, *args)
