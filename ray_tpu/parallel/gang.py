"""Gang: slice-aware SPMD worker group.

The TPU-defining layer (SURVEY.md §7 M3).  Replaces the reference's
WorkerGroup + out-of-band NCCL rendezvous (reference:
train/_internal/worker_group.py:92 + train/torch/config.py:69
_setup_torch_process_group) with slice-native formation:

  * single host (this round's fast path): ONE in-process member owns all
    local chips — jax is single-controller per host, so the driver itself
    drives the mesh; no process hop, no serialization of arrays.
  * multi host: one member process per TPU host, co-initialized with
    ``jax.distributed.initialize`` (coordinator = rank-0 member), each
    running the same compiled program (SPMD).  Members are actors with
    ``num_tpus`` resources so the scheduler places them on TPU hosts.

The gang is the unit of fault tolerance: a member death breaks the ICI
mesh, so recovery = rebuild the gang and restore from checkpoint
(reference restart-based analogue: backend_executor.py:571 _restart).
"""

from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import jax
from jax.sharding import Mesh

from ray_tpu.core import fault_injection as _fi
from ray_tpu.parallel.mesh import batch_sharding, create_mesh, mesh_shape


class GangMemberDied(RuntimeError):
    """A member actor died (or its call failed) during a collective
    gang operation.  Carries the rank so elastic recovery can name
    survivors without parsing error strings."""

    def __init__(self, rank: int, message: str):
        self.rank = rank
        super().__init__(message)


def _gather(refs: list, timeout: Optional[float], what: str) -> list:
    """Collective get with PER-MEMBER completion watching: the first
    member failure surfaces immediately as GangMemberDied naming the
    rank, instead of blocking until the stragglers a dead/failed peer
    has wedged (e.g. the rest of a formation barrier) time out."""
    import ray_tpu
    from ray_tpu.core.client import GetTimeoutError
    deadline = None if timeout is None else time.monotonic() + timeout
    pending = {ref: i for i, ref in enumerate(refs)}
    out: list = [None] * len(refs)
    while pending:
        ready, _ = ray_tpu.wait(list(pending), num_returns=len(pending),
                                timeout=1.0)
        for ref in ready:
            i = pending.pop(ref)
            try:
                out[i] = ray_tpu.get([ref])[0]
            except Exception as e:
                raise GangMemberDied(
                    i, f"gang member rank {i}/{len(refs)} failed during "
                       f"{what}: {e}") from e
        if deadline is not None and time.monotonic() > deadline and pending:
            raise GetTimeoutError(
                f"gang {what} timed out; ranks still pending: "
                f"{sorted(pending.values())}")
    return out


@dataclass
class GangConfig:
    mesh_axes: dict[str, int] = field(default_factory=lambda: {"dp": -1})
    num_hosts: int = 1
    use_cpu_devices: bool = False  # tests: virtual CPU mesh


class TpuGang:
    """Handle to a formed gang.  `run(fn, *args)` executes `fn` inside the
    mesh context on every member (single-host: inline)."""

    def __init__(self, config: Optional[GangConfig] = None,
                 devices: Optional[list] = None):
        self.config = config or GangConfig()
        if devices is None:
            devices = (jax.devices("cpu") if self.config.use_cpu_devices
                       else jax.devices())
        self.devices = devices
        self.mesh: Mesh = create_mesh(self.config.mesh_axes, devices=devices)
        self.num_hosts = self.config.num_hosts

    # -- info -------------------------------------------------------------

    @property
    def axis_sizes(self) -> dict[str, int]:
        return mesh_shape(self.mesh)

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    # -- execution ---------------------------------------------------------

    def run(self, fn: Callable, *args, **kwargs) -> Any:
        """Execute fn with the gang mesh active (single-host inline)."""
        with self.mesh:
            return fn(*args, **kwargs)

    def put_batch(self, batch: Any) -> Any:
        """Host batch pytree -> sharded jax.Arrays over the data axes."""
        sh = batch_sharding(self.mesh)
        return jax.tree.map(lambda x: jax.device_put(x, sh), batch)

    def shutdown(self) -> None:
        pass


def form_gang(mesh_axes: Optional[dict[str, int]] = None,
              use_cpu_devices: bool = False) -> TpuGang:
    return TpuGang(GangConfig(mesh_axes=mesh_axes or {"dp": -1},
                              use_cpu_devices=use_cpu_devices))


# ---------------------------------------------------------------------------
# multi-host formation (skeleton — exercised via dryrun in round 1)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _routable_ip() -> str:
    """This host's address as seen by peers (UDP-connect trick; falls
    back to loopback on isolated machines)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        s.connect(("8.8.8.8", 80))
        return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"
    finally:
        s.close()


class GangMember:
    """Actor body for one host's member process (multi-host path).

    Placed with ``num_tpus=<chips per host>`` so the scheduler reserves a
    whole host's chips; rank 0's address is the jax.distributed
    coordinator (the analogue of the reference's TCP-store rendezvous on
    the rank-0 train worker, train/torch/config.py:69).  With
    ``cpu_backend`` the member pins jax to N virtual CPU devices before
    backend init — the multi-host test shape (collectives ride Gloo).
    """

    def __init__(self, rank: int, world: int,
                 cpu_backend: bool = False, local_device_count: int = 0):
        self.rank = rank
        self.world = world
        self.cpu_backend = cpu_backend
        self.local_device_count = local_device_count
        self._initialized = False
        self._busy = False

    def choose_coordinator(self) -> str:
        """Rank 0 picks the rendezvous address ON ITS OWN HOST (the
        driver's loopback would be unreachable from other nodes)."""
        ip = _routable_ip()
        return f"{ip}:{_free_port()}"

    def _pin_backend(self) -> None:
        import jax as _jax
        if self.cpu_backend:
            # must land before first backend touch in this fresh process
            _jax.config.update("jax_platforms", "cpu")
            from ray_tpu.parallel.jax_compat import \
                enable_cpu_gloo_collectives
            enable_cpu_gloo_collectives()
            if self.local_device_count:
                try:
                    _jax.config.update("jax_num_cpu_devices",
                                       self.local_device_count)
                except AttributeError:
                    # pre-0.5 jax spelling; same pre-backend-init timing
                    import os as _os
                    _os.environ["XLA_FLAGS"] = (
                        _os.environ.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count="
                        + str(self.local_device_count))

    def _info(self) -> dict:
        import jax as _jax
        return {"rank": self.rank,
                "global_devices": len(_jax.devices()),
                "local_devices": len(_jax.local_devices()),
                "pid": __import__("os").getpid()}

    def setup(self, coordinator: str) -> dict:
        from ray_tpu.parallel.jax_compat import distributed_initialize
        self._pin_backend()
        if self.world > 1 and not self._initialized:
            # resilient client: a PEER's death must surface as a
            # collective error here, not terminate this process — the
            # property the elastic gang is built on (jax_compat)
            distributed_initialize(coordinator, self.world, self.rank)
            self._initialized = True
        return self._info()

    def reinit(self, coordinator: str, world: int, rank: int) -> dict:
        """Leave the current (possibly poisoned) distributed world IN
        PLACE — same process, same pid — and join a new one at the new
        world size/rank.  The elastic re-gang step: abandon (no
        collective barrier), drop cached backends so the global device
        view shrinks/grows, re-initialize."""
        from ray_tpu.parallel.jax_compat import (clear_backends,
                                                 distributed_abandon,
                                                 distributed_initialize)
        self._await_idle()
        if self._initialized:
            distributed_abandon()
            self._initialized = False
        clear_backends()
        self.rank = rank
        self.world = world
        if world > 1:
            distributed_initialize(coordinator, world, rank)
            self._initialized = True
        return self._info()

    def run(self, pickled_fn: bytes, *args):
        import cloudpickle
        fn = cloudpickle.loads(pickled_fn)
        self._busy = True
        try:
            return fn(self.rank, *args)
        finally:
            self._busy = False

    def _await_idle(self, timeout: float = 45.0) -> None:
        """A reform may land while this member's run() thread is still
        wedged in a collective its dead peer poisoned; tearing the
        backend down under a live computation is undefined.  Gloo
        surfaces peer death as an error within seconds, so wait for the
        attempt to unwind before abandoning the world."""
        deadline = time.monotonic() + timeout
        while getattr(self, "_busy", False) and time.monotonic() < deadline:
            time.sleep(0.05)

    def ping(self) -> dict:
        """Liveness probe; dispatched concurrently with run() (the gang
        creates members with max_concurrency>1), so a member wedged in
        a broken collective still answers."""
        import os
        return {"rank": self.rank, "pid": os.getpid()}

    def pid(self) -> int:
        import os
        return os.getpid()


class MultiHostGang:
    """A formed multi-host gang: one GangMember actor per host, jointly
    initialized through jax.distributed (SPMD across processes).

    The reference analogue is the worker-group half of BackendExecutor
    (reference: train/_internal/backend_executor.py:94 start +
    worker_group.py:92); formation here is one collective
    jax.distributed.initialize instead of a framework process-group
    bootstrap.

    The gang is ELASTIC: a member death no longer forces a full restart.
    ``reform(survivors)`` re-forms the gang at reduced world size from
    the SURVIVING member actors — same processes, same pids, fresh
    coordinator, fresh jax.distributed world, dp axis resharded to the
    new world — and ``readmit()`` grows it back toward the target size
    with replacement actors at the next re-gang boundary.  Full teardown
    + re-formation (reference: backend_executor.py:571 restart) remains
    the fallback when no member survives or reform itself fails.
    """

    def __init__(self, num_members: int, *, num_tpus_per_member: float = 0,
                 cpu_backend: bool = False, devices_per_member: int = 0,
                 resources_per_member: Optional[dict] = None,
                 setup_timeout: float = 120.0,
                 member_cls: Optional[type] = None):
        import ray_tpu

        self.num_members = num_members
        self.target_members = num_members
        self.setup_timeout = setup_timeout
        self._cpu_backend = cpu_backend
        self._devices_per_member = devices_per_member
        opts: dict = {"max_concurrency": 4}   # ping/reinit beside run
        if num_tpus_per_member:
            opts["num_tpus"] = num_tpus_per_member
        if resources_per_member:
            opts["resources"] = resources_per_member
        self._actor_cls = ray_tpu.remote(member_cls or GangMember) \
            .options(**opts)
        self.members = [
            self._actor_cls.remote(rank=i, world=num_members,
                                   cpu_backend=cpu_backend,
                                   local_device_count=devices_per_member)
            for i in range(num_members)]
        try:
            # rank 0 picks the rendezvous address on ITS host (it may be
            # scheduled on any node), then setup is a collective barrier:
            # all members must be in flight together.  _gather surfaces
            # the FIRST failed setup promptly — the others are wedged in
            # a barrier that can no longer complete.
            self.coordinator = ray_tpu.get(
                self.members[0].choose_coordinator.remote(),
                timeout=setup_timeout)
            self.infos = _gather(
                [m.setup.remote(self.coordinator) for m in self.members],
                setup_timeout, "formation setup")
        except BaseException:
            # partial formation must not leak the members that DID come
            # up: one failed/timed-out setup used to leave world-1
            # actors alive (and holding TPU reservations) forever
            self.shutdown()
            raise
        self.global_devices = self.infos[0]["global_devices"]

    # ----------------------------------------------------------- execution

    def run(self, fn: Callable, *args,
            timeout: Optional[float] = None) -> list:
        """Run ``fn(rank, *args)`` on every member; returns per-rank
        results (SPMD: all ranks execute the same program).  No default
        timeout: a member-side attempt may legitimately run for hours.

        Completion is watched PER MEMBER: the first failure — actor
        death or member exception — surfaces immediately as
        ``GangMemberDied`` naming the rank, instead of blocking on
        stragglers a dead peer has wedged in a broken collective."""
        import cloudpickle
        payload = cloudpickle.dumps(fn)
        return _gather([m.run.remote(payload, *args)
                        for m in self.members], timeout, "run")

    def member_pids(self) -> list[int]:
        import ray_tpu
        return ray_tpu.get([m.pid.remote() for m in self.members],
                           timeout=60)

    # ------------------------------------------------------------ elasticity

    def alive_ranks(self, timeout: float = 15.0) -> list[int]:
        """Probe every member concurrently; returns the ranks that still
        answer.  One shared deadline over ALL probes — a handful of
        wedged members must cost one window, not one window each.  Death
        errors surface promptly (event-driven actor-death sealing), so
        the common case costs one round-trip."""
        import ray_tpu
        probes = [(i, m.ping.remote()) for i, m in enumerate(self.members)]
        ready, _ = ray_tpu.wait([r for _, r in probes],
                                num_returns=len(probes), timeout=timeout)
        ready_set = set(ready)
        out = []
        for i, ref in probes:
            if ref not in ready_set:
                continue   # unresponsive within the window: not alive
            try:
                ray_tpu.get([ref], timeout=5)
                out.append(i)
            except Exception:
                pass       # sealed as an actor-death error: dead
        return out

    def reform(self, survivors: list[int]) -> None:
        """Re-form the gang from the surviving member actors at world
        size ``len(survivors)`` — their PROCESSES are kept (same pids);
        only the jax.distributed world is torn down and rebuilt, with
        the dp axis implicitly resharded to the new global device set.
        Dead members' actor handles are reaped."""
        import ray_tpu
        if not survivors:
            raise ValueError("reform needs at least one survivor")
        survivors = sorted(survivors)
        dead = [m for i, m in enumerate(self.members) if i not in survivors]
        keep = [self.members[i] for i in survivors]
        world = len(keep)
        # new rank 0 picks a FRESH coordinator on its host (the old
        # coordinator may have died with rank 0, and a stale service
        # must never adopt the new world)
        self.coordinator = ray_tpu.get(
            keep[0].choose_coordinator.remote(), timeout=self.setup_timeout)
        refs = [m.reinit.remote(self.coordinator, world, i)
                for i, m in enumerate(keep)]
        self.infos = _gather(refs, self.setup_timeout, "reform")
        self.members = keep
        self.num_members = world
        self.global_devices = self.infos[0]["global_devices"]
        for m in dead:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass

    def _chaos(self, point: str, **ctx) -> None:
        """Chaos-plane trigger at gang-membership boundaries
        (hotpath_registry contract: disarmed = one global load +
        is-None branch).  Runs driver-side, so scripted schedules fire
        deterministically in-process."""
        fi = _fi._active
        if fi is None:
            return
        ctx.setdefault("world", self.num_members)
        fi.on_gang(point, ctx)

    def readmit(self, count: Optional[int] = None) -> int:
        """Grow the gang back toward ``target_members`` with REPLACEMENT
        member actors (fresh processes), re-initializing the whole world
        at the larger size.  Survivor processes are still kept — this is
        the "re-admit a replacement host at the next re-gang boundary"
        step.  Returns the new world size."""
        import ray_tpu
        want = self.target_members - self.num_members \
            if count is None else count
        if want <= 0:
            return self.num_members
        self._chaos("gang_readmit", target=self.target_members,
                    want=want)
        world = self.num_members + want
        fresh = [
            self._actor_cls.remote(rank=self.num_members + j, world=world,
                                   cpu_backend=self._cpu_backend,
                                   local_device_count=self._devices_per_member)
            for j in range(want)]
        try:
            self.coordinator = ray_tpu.get(
                self.members[0].choose_coordinator.remote(),
                timeout=self.setup_timeout)
            refs = [m.reinit.remote(self.coordinator, world, i)
                    for i, m in enumerate(self.members)]
            refs += [m.setup.remote(self.coordinator) for m in fresh]
            self.infos = _gather(refs, self.setup_timeout, "readmit")
        except BaseException:
            for m in fresh:   # don't leak half-admitted replacements
                try:
                    ray_tpu.kill(m)
                except Exception:
                    pass
            raise
        self.members = self.members + fresh
        self.num_members = world
        self.global_devices = self.infos[0]["global_devices"]
        return world

    def shutdown(self) -> None:
        import ray_tpu
        for m in self.members:
            try:
                ray_tpu.kill(m)
            except Exception:
                pass
