"""Logical-axis sharding rules.

The TPU-native replacement for per-framework process-group setup
(reference capability: torch DDP wraps modules per-rank,
python/ray/train/torch/config.py:113 — here parallelism is declared as a
mapping from *logical* tensor axes to mesh axes and applied with pjit;
XLA inserts the collectives).  Same idea as flax's logical partitioning,
kept dependency-light so any pytree of params works.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# rules: logical axis name -> mesh axis (or tuple of mesh axes, or None)
Rules = dict[str, Union[str, tuple[str, ...], None]]

# A sensible default for transformer LLMs on a dp/fsdp/tp/sp mesh
# (scaling-book style: batch over dp+fsdp, params sharded over fsdp,
# heads/mlp over tp, sequence over sp).
DEFAULT_LLM_RULES: Rules = {
    "batch": ("dcn", "dp", "fsdp"),
    "seq": "sp",
    "embed": None,
    "mlp": "tp",
    "heads": "tp",
    "kv": None,
    "qkv": "tp",
    "vocab": "tp",
    "expert": "ep",
    # layer stacks shard over pp (each pipeline stage holds a contiguous
    # block); _prune drops the rule on meshes without a pp axis
    "layers": "pp",
    "stage": "pp",
}


def _prune(rule, mesh: Mesh):
    """Drop mesh axes absent from `mesh` (or of size 1)."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    if rule is None:
        return None
    if isinstance(rule, str):
        rule = (rule,)
    kept = tuple(a for a in rule if shape.get(a, 1) > 1)
    if not kept:
        return None
    return kept if len(kept) > 1 else kept[0]


def spec_for(logical_axes: Sequence[Optional[str]], rules: Rules,
             mesh: Mesh) -> PartitionSpec:
    """logical axes of one array -> PartitionSpec on `mesh`."""
    used: set = set()
    out = []
    for ax in logical_axes:
        if ax is None:
            out.append(None)
            continue
        rule = _prune(rules.get(ax), mesh)
        # a mesh axis may appear at most once in a spec
        if rule is not None:
            axes = (rule,) if isinstance(rule, str) else rule
            axes = tuple(a for a in axes if a not in used)
            used.update(axes)
            rule = axes if len(axes) > 1 else (axes[0] if axes else None)
            if rule == ():
                rule = None
        out.append(rule)
    return PartitionSpec(*out)


def sharding_for(logical_axes: Sequence[Optional[str]], rules: Rules,
                 mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, spec_for(logical_axes, rules, mesh))


def tree_shardings(logical_tree: Any, rules: Rules, mesh: Mesh) -> Any:
    """Map a pytree whose leaves are tuples of logical axis names to a
    pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: sharding_for(axes, rules, mesh),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple)
        and all(isinstance(e, (str, type(None))) for e in x))


def infer_param_logical_axes(params: Any) -> Any:
    """Heuristic logical axes for a params pytree when the model doesn't
    declare them: shard the largest dim of ≥2D params over fsdp-style
    'embed'/'mlp' axes, replicate the rest.  Used as a fallback — models
    in ray_tpu.models declare axes explicitly."""
    def leaf_axes(x):
        if not hasattr(x, "ndim") or x.ndim < 2:
            return (None,) * getattr(x, "ndim", 0) if hasattr(x, "ndim") else None
        axes: list[Optional[str]] = [None] * x.ndim
        axes[int(max(range(x.ndim), key=lambda i: x.shape[i]))] = "mlp"
        return tuple(axes)

    return jax.tree.map(leaf_axes, params)


def constrain(x: Any, logical_axes: Sequence[Optional[str]], rules: Rules,
              mesh: Mesh) -> Any:
    """with_sharding_constraint by logical axes (no-op outside jit)."""
    return jax.lax.with_sharding_constraint(
        x, sharding_for(logical_axes, rules, mesh))
