"""1F1B pipeline schedule: fused forward+backward with bounded
activation liveness.

GPipe (parallel/pipeline.py) runs ALL forwards, then reverse-mode AD
replays them backwards — every stage must hold M microbatch inputs
live.  1F1B (PipeDream-flush / Megatron's non-interleaved schedule)
starts microbatch i's backward as soon as it leaves the last stage, so
a stage holds at most S in-flight activations: the activation footprint
drops from O(M) to O(S) microbatches (M = 2S halves it; long schedules
gain more).  Same bubble fraction as GPipe.

Autodiff cannot express this — jax.grad over a forward program runs the
whole forward first — so the schedule here is a MANUAL value-and-grads
program: one ``lax.scan`` over ticks under ``shard_map`` manual over
``pp``; each tick a stage takes its scheduled action (branchy
``lax.cond`` — cores diverge for real in manual mode, so a tick costs
one action, not all of them):

  * F(i): apply the stage block to microbatch i's input, stash the
    input in slot i mod S, hand the output right (ppermute).
  * B(i): re-linearize the stage at the stashed input (jax.vjp =
    recompute + backward — activation-memory-optimal, compute parity
    with GPipe+remat), apply the incoming cotangent, accumulate the
    local parameter gradient, hand the input-cotangent left.
  * last stage folds the loss tail (head + CE) into B, so its F only
    stashes.

The schedule table (which action each stage takes at each tick, and
what the hand-off wires carry) is SIMULATED host-side at trace time and
validated for dependency- and stash-safety, then baked into the scan as
static arrays — the compiled program has no data-dependent control
flow.

Green-field vs the reference (no pipeline engine at all, SURVEY.md
§2.4); schedule shape follows Megatron/PipeDream-flush.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.jax_compat import shard_map


class Schedule(NamedTuple):
    """Static per-(tick, stage) action tables."""
    do_f: np.ndarray       # [T, S] bool
    f_mb: np.ndarray       # [T, S] int32
    do_b: np.ndarray       # [T, S] bool
    b_mb: np.ndarray       # [T, S] int32
    recv_f: np.ndarray     # [T, S] bool  — store arriving fwd hand-off
    recv_f_mb: np.ndarray  # [T, S] int32
    recv_b: np.ndarray     # [T, S] bool  — store arriving bwd hand-off
    recv_b_mb: np.ndarray  # [T, S] int32


def build_1f1b_schedule(S: int, M: int) -> Schedule:
    """Greedy simulation of the non-interleaved 1F1B schedule, with
    dependency + stash-slot safety asserted."""
    assert M >= S, f"1F1B needs microbatches >= stages ({M} < {S})"
    f_done = [[-1] * M for _ in range(S)]   # tick F(i) completed
    b_done = [[-1] * M for _ in range(S)]
    next_f = [0] * S
    next_b = [0] * S
    # per-stage action pattern: warmup forwards, then 1F1B, then drain
    warmup = [min(S - 1 - r, M) for r in range(S)]
    actions: list[list[tuple]] = [[] for _ in range(S)]

    t = 0
    while any(next_b[r] < M for r in range(S)) and t < 8 * (M + S):
        acts = []
        for r in range(S):
            act = None
            want_f = next_f[r] < M
            want_b = next_b[r] < M
            # steady-state preference: after warmup forwards, do B
            # before the next F (that's what bounds liveness to S)
            prefer_b = want_b and next_f[r] >= warmup[r] + next_b[r]
            order = (("B", "F") if prefer_b or not want_f else ("F", "B"))
            for kind in order:
                if kind == "F" and want_f:
                    i = next_f[r]
                    ready = (r == 0 or (0 <= f_done[r - 1][i] < t))
                    # stash slot i%S must be free: B(i-S) already done
                    slot_free = i < S or b_done[r][i - S] >= 0
                    if ready and slot_free:
                        act = ("F", i)
                        break
                if kind == "B" and want_b:
                    i = next_b[r]
                    ready = (0 <= f_done[r][i] < t if r == S - 1
                             else 0 <= b_done[r + 1][i] < t)
                    if ready:
                        act = ("B", i)
                        break
            acts.append(act)
        for r, act in enumerate(acts):
            if act is None:
                continue
            kind, i = act
            if kind == "F":
                f_done[r][i] = t
                next_f[r] += 1
            else:
                b_done[r][i] = t
                next_b[r] += 1
        for r in range(S):
            actions[r].append(acts[r])
        t += 1
    assert all(next_b[r] == M for r in range(S)), "1F1B schedule stuck"
    T = t

    do_f = np.zeros((T, S), bool)
    f_mb = np.zeros((T, S), np.int32)
    do_b = np.zeros((T, S), bool)
    b_mb = np.zeros((T, S), np.int32)
    for r in range(S):
        for tt, act in enumerate(actions[r]):
            if act is None:
                continue
            kind, i = act
            if kind == "F":
                do_f[tt, r] = True
                f_mb[tt, r] = i
            else:
                do_b[tt, r] = True
                b_mb[tt, r] = i

    # hand-off receive tables: what arrives at tick t was sent at t-1
    recv_f = np.zeros((T, S), bool)
    recv_f_mb = np.zeros((T, S), np.int32)
    recv_b = np.zeros((T, S), bool)
    recv_b_mb = np.zeros((T, S), np.int32)
    for tt in range(1, T):
        for r in range(S):
            if r > 0 and do_f[tt - 1, r - 1]:
                recv_f[tt, r] = True
                recv_f_mb[tt, r] = f_mb[tt - 1, r - 1]
            if r < S - 1 and do_b[tt - 1, r + 1]:
                recv_b[tt, r] = True
                recv_b_mb[tt, r] = b_mb[tt - 1, r + 1]
    return Schedule(do_f, f_mb, do_b, b_mb,
                    recv_f, recv_f_mb, recv_b, recv_b_mb)


def pipeline_value_and_grads_1f1b(
        stage_fn: Callable[[Any, jax.Array], jax.Array],
        last_fn: Callable[[Any, jax.Array, jax.Array], jax.Array],
        x_mb: jax.Array, y_mb: jax.Array,
        stage_params: Any, last_params: Any, *,
        mesh: Mesh, axis: str = "pp"):
    """Fused 1F1B training pass.

    Args:
      stage_fn: ``(local_stage_params, x) -> x`` one stage's block.
      last_fn: ``(last_params, x, y) -> scalar`` loss tail (final norm +
        head + CE) applied to the LAST stage's output per microbatch —
        must return the SUM-convention loss contribution of one
        microbatch such that total loss = mean over microbatches.
      x_mb: [M, mb, ...] pipeline inputs (post-embedding).
      y_mb: [M, mb, ...] per-microbatch targets.
      stage_params: leading-dim layers pytree, sharded over ``axis``.
      last_params: loss-tail params, replicated.

    Returns ``(loss, d_stage_params, d_last_params, d_x_mb)`` — plug
    d_x_mb into the embedding's vjp outside.
    """
    S = mesh.shape[axis]
    M = x_mb.shape[0]
    sched = build_1f1b_schedule(S, M)
    T = sched.do_f.shape[0]
    tables = jax.tree.map(jnp.asarray, sched)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [((i + 1) % S, i) for i in range(S)]
    inv_m = 1.0 / M

    def body(x_mb, y_mb, lp, tp):
        r = lax.axis_index(axis)
        is_last = r == S - 1

        def stage_and_tail(p_stage, p_tail, x, y):
            out = stage_fn(p_stage, x)
            return last_fn(p_tail, out, y) * inv_m

        def tick(carry, tab):
            (stash, dstash, fwd_wire, bwd_wire, dP, dT, dX, loss) = carry
            (do_f, f_mb, do_b, b_mb,
             recv_f, recv_f_mb, recv_b, recv_b_mb) = [x[r] for x in tab]

            # 1. bank last tick's hand-offs into the slot stashes
            stash = lax.cond(
                recv_f,
                lambda s: s.at[recv_f_mb % S].set(fwd_wire), lambda s: s,
                stash)
            dstash = lax.cond(
                recv_b,
                lambda s: s.at[recv_b_mb % S].set(bwd_wire), lambda s: s,
                dstash)

            # 2. forward action
            def run_f(args):
                stash, wire = args
                x_in = jnp.where(r == 0, x_mb[f_mb], stash[f_mb % S])
                stash = stash.at[f_mb % S].set(x_in)
                # the last stage folds its compute into B: F just
                # stashes, the wire content is unused there
                y = lax.cond(is_last, lambda: x_in,
                             lambda: stage_fn(lp, x_in))
                return stash, y

            stash, fwd_out = lax.cond(
                do_f, run_f, lambda a: (a[0], a[1]),
                (stash, fwd_wire))

            # 3. backward action (re-linearize at the stashed input)
            def run_b(args):
                dP, dT, dX, loss = args
                x_in = stash[b_mb % S]

                def at_last():
                    l, vjp = jax.vjp(
                        lambda ps, pt, xi: stage_and_tail(
                            ps, pt, xi, y_mb[b_mb]), lp, tp, x_in)
                    dp, dt, dx = vjp(jnp.ones(()))
                    return l, dp, dt, dx

                def mid():
                    _, vjp = jax.vjp(lambda ps, xi: stage_fn(ps, xi),
                                     lp, x_in)
                    dp, dx = vjp(dstash[b_mb % S])
                    return jnp.zeros(()), dp, \
                        jax.tree.map(jnp.zeros_like, tp), dx

                l, dp, dt, dx = lax.cond(is_last, at_last, mid)
                dP = jax.tree.map(jnp.add, dP, dp)
                dT = jax.tree.map(jnp.add, dT, dt)
                loss = loss + l
                # stage 0's input-cotangent belongs to the embedding
                dX = lax.cond(r == 0,
                              lambda b: b.at[b_mb].set(dx), lambda b: b,
                              dX)
                return (dP, dT, dX, loss), dx

            (dP, dT, dX, loss), bwd_out = lax.cond(
                do_b, run_b,
                lambda a: (a, bwd_wire), (dP, dT, dX, loss))

            # 4. hand-offs for the next tick
            fwd_wire = lax.ppermute(fwd_out, axis, fwd_perm)
            bwd_wire = lax.ppermute(bwd_out, axis, bwd_perm)
            return (stash, dstash, fwd_wire, bwd_wire, dP, dT, dX,
                    loss), None

        mb_shape = x_mb.shape[1:]
        zeros_act = jnp.zeros((S,) + mb_shape, x_mb.dtype)
        carry0 = (zeros_act, zeros_act,
                  jnp.zeros(mb_shape, x_mb.dtype),
                  jnp.zeros(mb_shape, x_mb.dtype),
                  jax.tree.map(jnp.zeros_like, lp),
                  jax.tree.map(jnp.zeros_like, tp),
                  jnp.zeros_like(x_mb),
                  jnp.zeros(()))
        (stash, dstash, _, _, dP, dT, dX, loss), _ = lax.scan(
            tick, carry0, tables)
        # loss and tail grads live on the last stage; dX on stage 0 —
        # psum replicates each (zeros elsewhere).  dP stays LOCAL: its
        # out_spec concatenates the per-stage layer blocks back into
        # the full leading-layers gradient.
        loss = lax.psum(loss, axis)
        dT = jax.tree.map(lambda v: lax.psum(v, axis), dT)
        dX = lax.psum(dX, axis)
        return (loss[None], dP,
                jax.tree.map(lambda v: v[None], dT), dX[None])

    in_specs = (P(), P(), jax.tree.map(lambda _: P(axis), stage_params),
                jax.tree.map(lambda _: P(), last_params))
    out_specs = (P(axis), jax.tree.map(lambda _: P(axis), stage_params),
                 jax.tree.map(lambda _: P(axis), last_params), P(axis))
    loss, dP, dT, dX = shard_map(
        body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        axis_names={axis}, check_vma=False)(x_mb, y_mb, stage_params,
                                            last_params)
    return (loss[0], dP, jax.tree.map(lambda v: v[0], dT), dX[0])
