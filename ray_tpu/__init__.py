"""ray_tpu: a TPU-native distributed computing framework.

Task/actor/object core runtime (reference capability: ray core —
python/ray/__init__.py surface) with a TPU-first ML stack on top:
compiled-SPMD parallelism (ray_tpu.parallel), training (ray_tpu.train),
tuning (ray_tpu.tune), datasets (ray_tpu.data), RL (ray_tpu.rllib), and
serving (ray_tpu.serve).
"""

from ray_tpu._version import __version__  # noqa: F401
from ray_tpu.core.runtime import (init, shutdown, is_initialized,
                                  get_runtime)
from ray_tpu.core.remote_function import remote
from ray_tpu.core.actor import (get_actor, kill, ActorHandle,
                                list_named_actors)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.client import (TaskError, GetTimeoutError, ActorDiedError,
                                 ObjectLostError, OutOfMemoryError,
                                 RetryPolicy)
from ray_tpu.core.placement_group import (placement_group,
                                          remove_placement_group,
                                          PlacementGroup,
                                          PlacementGroupSchedulingStrategy)


def put(value):
    """Store an object and return a reference (reference: ray.put,
    python/ray/_private/worker.py:2406)."""
    return get_runtime().put(value)


def get(refs, *, timeout=None):
    """Resolve ObjectRef(s) to values (reference: ray.get,
    python/ray/_private/worker.py:2273)."""
    single = isinstance(refs, ObjectRef)
    if single:
        refs = [refs]
    refs = list(refs)
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get takes ObjectRefs, got {type(r)}")
    out = get_runtime().get(refs, timeout=timeout)
    return out[0] if single else out


def wait(refs, *, num_returns=1, timeout=None):
    """Wait for num_returns of refs to be ready (reference: ray.wait)."""
    refs = list(refs)
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds number of refs")
    return get_runtime().wait(refs, num_returns=num_returns, timeout=timeout)


def free(refs):
    """Eagerly delete objects from the object plane."""
    return get_runtime().free(list(refs))


def object_store_stats():
    """Node object-store stats (size, spill counters, backend)."""
    rt = get_runtime()
    return rt.client.request({"t": "object_stats"})["stats"]


def nodes():
    """Cluster membership view (reference: ray.nodes())."""
    rt = get_runtime()
    return rt.client.request({"t": "state", "what": "nodes"})["data"]


def drain_node(node_id: str, deadline_s: float = 30.0):
    """Gracefully decommission a cluster node (reference: the
    autoscaler's DrainNode request before terminating an instance).
    The node goes ACTIVE -> DRAINING -> TERMINATED: no new task or
    actor placements, queued specs re-park to the head, running tasks
    get ``deadline_s`` to finish, then owned objects and ownership
    records hand off to a survivor and the node exits — a planned
    removal, never something peers mistake for a crash.  Past the
    deadline the node exits anyway and the remaining recovery runs the
    normal (lineage) failure path, explicitly."""
    rt = get_runtime()
    return rt.client.request({"t": "drain_node", "node_id": node_id,
                              "deadline_s": float(deadline_s)})


def timeline(filename=None):
    """Chrome-trace task timeline (reference: ray.timeline)."""
    from ray_tpu.util.state import timeline as _timeline
    return _timeline(filename)


def available_resources():
    rt = get_runtime()
    return rt.client.request({"t": "state", "what": "resources"})["data"]["available"]


def cluster_resources():
    rt = get_runtime()
    return rt.client.request({"t": "state", "what": "resources"})["data"]["total"]


__all__ = [
    "__version__", "init", "shutdown", "is_initialized", "remote", "put",
    "get", "wait", "free", "get_actor", "list_named_actors", "kill",
    "ActorHandle", "ObjectRef",
    "ObjectRefGenerator", "TaskError", "GetTimeoutError", "ActorDiedError",
    "ObjectLostError", "OutOfMemoryError", "RetryPolicy",
    "placement_group", "remove_placement_group", "PlacementGroup",
    "PlacementGroupSchedulingStrategy", "available_resources",
    "cluster_resources", "drain_node", "nodes", "timeline",
]
