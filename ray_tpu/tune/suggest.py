"""Model-based searchers: TPE, GP-EI, and budget-aware BOHB.

Native re-derivations of the reference's external-library searcher
families (reference: python/ray/tune/search/hyperopt/ wraps TPE,
search/bayesopt/ wraps GP-EI, search/bohb/ wraps BOHB) — implemented
directly on numpy so the framework carries no optional dependencies.

All operate on the sample-space primitives in ``ray_tpu.tune.search``:
Uniform / LogUniform / RandInt are continuous (log-transformed where
appropriate), Choice is categorical.  Nested dicts flatten to
path-tuples.
"""

from __future__ import annotations

import math
from typing import Any, Optional

import numpy as np

from ray_tpu.tune.search import (Choice, Domain, GridSearch, LogUniform,
                                 RandInt, Searcher, Uniform)

# -- shared GP machinery ----------------------------------------------------


def gp_posterior(X: np.ndarray, y: np.ndarray, cands: np.ndarray,
                 length_scale: float, noise: float = 1e-4):
    """RBF-kernel GP posterior at candidate points.

    Returns (mu, sigma) of the normalized-target posterior plus the
    normalization (mean, sd) so callers can invert it. Shared by
    GPSearcher (EI) and the PB2 scheduler (UCB) — one copy of the
    kernel/solve math."""
    mu0, sd = float(y.mean()), max(float(y.std()), 1e-9)
    yn = (y - mu0) / sd

    def kernel(a, b):
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / length_scale ** 2)

    K = kernel(X, X) + noise * np.eye(len(X))
    Kinv = np.linalg.inv(K)
    Kc = kernel(cands, X)
    mu = Kc @ (Kinv @ yn)
    var = np.maximum(1.0 - np.einsum("ij,jk,ik->i", Kc, Kinv, Kc), 1e-12)
    return mu, np.sqrt(var), (mu0, sd)


# -- space flattening -------------------------------------------------------


def _flatten_space(space: dict, prefix=()) -> dict[tuple, Any]:
    out: dict[tuple, Any] = {}
    for k, v in space.items():
        key = (*prefix, k)
        if isinstance(v, dict):
            out.update(_flatten_space(v, key))
        else:
            out[key] = v
    return out


def _unflatten(flat: dict[tuple, Any]) -> dict:
    out: dict = {}
    for path, v in flat.items():
        d = out
        for k in path[:-1]:
            d = d.setdefault(k, {})
        d[path[-1]] = v
    return out


class _Dim:
    """One search dimension in a normalized [0,1] (continuous) or
    index (categorical) representation."""

    def __init__(self, domain):
        self.domain = domain
        self.categorical = isinstance(domain, Choice)
        if self.categorical:
            self.values = list(domain.values)
        elif isinstance(domain, LogUniform):
            self.lo, self.hi = math.log(domain.low), math.log(domain.high)
        elif isinstance(domain, (Uniform, RandInt)):
            self.lo, self.hi = float(domain.low), float(domain.high)
        else:
            raise TypeError(f"unsupported domain {domain!r}")

    def from_unit(self, u: float):
        if self.categorical:
            return self.values[int(u)]
        x = self.lo + min(max(u, 0.0), 1.0) * (self.hi - self.lo)
        if isinstance(self.domain, LogUniform):
            return math.exp(x)
        if isinstance(self.domain, RandInt):
            # floor, not truncation: int() would skew negative domains
            # toward zero relative to Domain.sample's randrange
            return min(math.floor(x), int(self.hi) - 1)
        return x

    def sample_unit(self, rng: np.random.RandomState) -> float:
        if self.categorical:
            return rng.randint(len(self.values))
        return rng.rand()


class _ModelSearcher(Searcher):
    """Shared bookkeeping: dims, observations, num_samples budget,
    random startup phase, mode normalization (scores are minimized
    internally)."""

    def __init__(self, param_space: dict, metric: Optional[str] = None,
                 mode: Optional[str] = None, num_samples: int = 64,
                 n_startup: int = 10, seed: Optional[int] = None):
        assert mode in (None, "min", "max")
        flat = _flatten_space(param_space)
        for k, v in flat.items():
            if isinstance(v, GridSearch):
                # grid semantics (try EVERY value) cannot be honored by a
                # sampling model — reject loudly, like the reference's
                # hyperopt/bayesopt searchers do
                raise ValueError(
                    f"grid_search (at {'.'.join(map(str, k))}) is not "
                    "supported by model-based searchers; use tune.choice "
                    "or BasicVariantGenerator")
        self.fixed = {k: v for k, v in flat.items()
                      if not isinstance(v, Domain)}
        self.dims = {k: _Dim(v) for k, v in flat.items()
                     if isinstance(v, Domain)}
        self._metric_explicit = metric is not None
        self._mode_explicit = mode is not None
        self.metric = metric or "loss"
        self.mode = mode or "min"
        self.num_samples = num_samples
        self.n_startup = n_startup
        self.rng = np.random.RandomState(seed)
        self._suggested = 0
        self._configs: dict[str, dict[tuple, float]] = {}  # unit space
        self._obs: list[tuple[dict[tuple, float], float]] = []

    def _record(self, trial_id: str, result: Optional[dict]) -> None:
        units = self._configs.pop(trial_id, None)
        if units is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        self._obs.append((units, score))

    def on_trial_complete(self, trial_id, result):
        self._record(trial_id, result)

    def set_search_properties(self, metric, mode):
        if metric and not self._metric_explicit:
            self.metric = metric
        if mode and not self._mode_explicit:
            self.mode = mode

    def _emit(self, trial_id: str, units: dict[tuple, float]) -> dict:
        self._configs[trial_id] = units
        self._suggested += 1
        flat = {}
        for k, v in self.fixed.items():
            # sample_from-style callables re-evaluate per trial, matching
            # BasicVariantGenerator (search.py _materialize)
            flat[k] = v() if callable(v) and not isinstance(v, type) else v
        for k, dim in self.dims.items():
            flat[k] = dim.from_unit(units[k])
        return _unflatten(flat)

    def _random_units(self) -> dict[tuple, float]:
        return {k: d.sample_unit(self.rng) for k, d in self.dims.items()}

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._suggested >= self.num_samples:
            return None
        if len(self._obs) < self.n_startup:
            return self._emit(trial_id, self._random_units())
        return self._emit(trial_id, self._model_units())

    # subclass hook
    def _model_units(self) -> dict[tuple, float]:
        raise NotImplementedError


class TPESearcher(_ModelSearcher):
    """Tree-structured Parzen Estimator (Bergstra et al. 2011,
    'Algorithms for Hyper-Parameter Optimization') — the algorithm the
    reference wraps via hyperopt (reference: tune/search/hyperopt/
    hyperopt_search.py).  Observations split into good (top gamma
    quantile) and bad; candidates sampled from the good kernel density
    are ranked by the density ratio l(x)/g(x), independently per
    dimension."""

    def __init__(self, param_space: dict, metric: str = "loss",
                 mode: str = "min", num_samples: int = 64,
                 n_startup: int = 10, gamma: float = 0.25,
                 n_candidates: int = 24, prior_weight: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(param_space, metric, mode, num_samples,
                         n_startup, seed)
        self.gamma = gamma
        self.n_candidates = n_candidates
        # fraction of candidates drawn from the uniform prior: keeps
        # exploration alive once the good set collapses onto one region
        # (hyperopt mixes the prior into the KDE the same way)
        self.prior_weight = prior_weight

    @staticmethod
    def _kde_logpdf(x: np.ndarray, centers: np.ndarray, bw: float):
        d = (x[:, None] - centers[None, :]) / bw
        log_k = -0.5 * d * d - math.log(bw * math.sqrt(2 * math.pi))
        m = log_k.max(axis=1, keepdims=True)
        return (m[:, 0] + np.log(np.exp(log_k - m).sum(axis=1))
                - math.log(len(centers)))

    def _model_units(self) -> dict[tuple, float]:
        scores = np.array([s for _, s in self._obs])
        n_good = max(1, int(math.ceil(self.gamma * len(scores))))
        order = np.argsort(scores)
        good_idx = set(order[:n_good].tolist())
        units = {}
        for k, dim in self.dims.items():
            vals = np.array([u[k] for u, _ in self._obs])
            good = vals[list(good_idx)]
            bad = np.array([v for i, v in enumerate(vals)
                            if i not in good_idx]) if len(vals) > n_good \
                else vals
            if dim.categorical:
                ncat = len(dim.values)
                pg = (np.bincount(good.astype(int), minlength=ncat) + 1.0)
                pb = (np.bincount(bad.astype(int), minlength=ncat) + 1.0)
                ratio = (pg / pg.sum()) / (pb / pb.sum())
                # candidates from the good distribution MIXED with the
                # uniform prior, ranked by the density ratio
                p = ((1 - self.prior_weight) * pg / pg.sum()
                     + self.prior_weight / ncat)
                cand = self.rng.choice(ncat, size=self.n_candidates,
                                       p=p / p.sum())
                units[k] = int(cand[np.argmax(ratio[cand])])
                continue
            # Scott-ish bandwidth floored so early clusters still explore
            bw = max(good.std() * len(good) ** -0.2, 0.08)
            cand = good[self.rng.randint(len(good), size=self.n_candidates)]
            cand = np.clip(cand + self.rng.randn(self.n_candidates) * bw,
                           0.0, 1.0)
            n_prior = max(1, int(self.prior_weight * self.n_candidates))
            cand[:n_prior] = self.rng.rand(n_prior)   # prior draws
            lg = self._kde_logpdf(cand, good, bw)
            lb = self._kde_logpdf(cand, bad if len(bad) else good,
                                  max(bad.std() * max(len(bad), 1) ** -0.2,
                                      0.08) if len(bad) else bw)
            units[k] = float(cand[np.argmax(lg - lb)])
        return units


class GPSearcher(_ModelSearcher):
    """Gaussian-process expected improvement over the unit cube
    (reference wraps the same method via bayes_opt:
    tune/search/bayesopt/bayesopt_search.py).  RBF kernel, categorical
    dims one-hot encoded, EI maximized over a random candidate pool."""

    def __init__(self, param_space: dict, metric: str = "loss",
                 mode: str = "min", num_samples: int = 64,
                 n_startup: int = 8, n_candidates: int = 256,
                 length_scale: float = 0.25, noise: float = 1e-4,
                 seed: Optional[int] = None):
        super().__init__(param_space, metric, mode, num_samples,
                         n_startup, seed)
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise

    def _vec(self, units: dict[tuple, float]) -> np.ndarray:
        parts = []
        for k, dim in self.dims.items():
            if dim.categorical:
                one = np.zeros(len(dim.values))
                one[int(units[k])] = 1.0
                parts.append(one)
            else:
                parts.append(np.array([units[k]]))
        return np.concatenate(parts)

    def _model_units(self) -> dict[tuple, float]:
        X = np.stack([self._vec(u) for u, _ in self._obs])
        y = np.array([s for _, s in self._obs])
        cands = [self._random_units() for _ in range(self.n_candidates)]
        Xc = np.stack([self._vec(u) for u in cands])
        mu, sigma, (mu0, sd) = gp_posterior(X, y, Xc,
                                            self.length_scale, self.noise)
        best = (y.min() - mu0) / sd
        z = (best - mu) / sigma
        phi = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        Phi = 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = sigma * (z * Phi + phi)
        return cands[int(np.argmax(ei))]


class TuneBOHB(TPESearcher):
    """BOHB's model half (Falkner et al. 2018): TPE conditioned on the
    highest training budget that has enough observations, designed to
    pair with HyperBandScheduler (reference: tune/search/bohb/ +
    schedulers/hb_bohb.py).  Intermediate results feed the model via
    on_trial_result so early-stopped trials still contribute at their
    budget."""

    def __init__(self, *args, min_points_in_model: Optional[int] = None,
                 **kw):
        super().__init__(*args, **kw)
        self.min_points = min_points_in_model or self.n_startup
        # budget (training_iteration) -> [(units, score)]
        self._by_budget: dict[int, list] = {}
        self._recorded: set[tuple[str, int]] = set()

    def _record_at_budget(self, trial_id: str, result: dict) -> None:
        units = self._configs.get(trial_id)
        if units is None or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "max":
            score = -score
        budget = int(result.get("training_iteration", 0))
        if (trial_id, budget) in self._recorded:
            return   # the final result arrives twice (result + complete)
        self._recorded.add((trial_id, budget))
        self._by_budget.setdefault(budget, []).append((units, score))

    def on_trial_result(self, trial_id: str, result: dict) -> None:
        # BOHB's defining trait: every rung evaluation is an observation
        # at its budget, so early-stopped trials still inform the model
        self._record_at_budget(trial_id, result)

    def on_trial_complete(self, trial_id, result):
        # result=None means the trial ERRORED — its pre-crash metrics
        # must not feed the model
        if result is not None:
            self._record_at_budget(trial_id, result)
        self._configs.pop(trial_id, None)
        self._recorded = {(t, b) for t, b in self._recorded
                          if t != trial_id}

    def _model_units(self) -> dict[tuple, float]:
        # model the largest budget with enough observations (BOHB rule)
        for budget in sorted(self._by_budget, reverse=True):
            obs = self._by_budget[budget]
            if len(obs) >= self.min_points:
                self._obs = obs
                return super()._model_units()
        # not enough anywhere: pool all budgets
        self._obs = [o for obs in self._by_budget.values() for o in obs]
        if len(self._obs) >= self.min_points:
            return super()._model_units()
        return self._random_units()

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._suggested >= self.num_samples:
            return None
        total = sum(len(v) for v in self._by_budget.values())
        if total < self.n_startup:
            return self._emit(trial_id, self._random_units())
        return self._emit(trial_id, self._model_units())
