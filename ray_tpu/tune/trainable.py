"""Trainable API (reference: tune/trainable/trainable.py:66 — train/step/
save/restore — and function_trainable.py wrap_function).

A Trainable is a stepwise training process the scheduler can stop,
checkpoint, and clone (PBT exploit).  Function trainables run their
function one "virtual step" per reported result via a generator bridge —
no thread, matching the single-controller design of the runtime.
"""

from __future__ import annotations

import inspect
import os
import pickle
import tempfile
from typing import Any, Callable, Optional


class Trainable:
    """Subclass API: setup(config), step() -> result dict,
    save_checkpoint() -> dict, load_checkpoint(dict)."""

    def __init__(self, config: Optional[dict] = None):
        self.config = config or {}
        self._iteration = 0
        self.setup(self.config)

    # -- subclass hooks ----------------------------------------------------

    def setup(self, config: dict):
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> dict:
        return {}

    def load_checkpoint(self, checkpoint: dict):
        pass

    def reset_config(self, new_config: dict) -> bool:
        """PBT explore hook; return True if handled without restart."""
        return False

    def cleanup(self):
        pass

    # -- runner-facing API (reference: trainable.py train:321/save:450) ----

    def train(self) -> dict:
        result = self.step()
        self._iteration += 1
        result.setdefault("training_iteration", self._iteration)
        return result

    def save(self) -> dict:
        return {"_iteration": self._iteration,
                "payload": self.save_checkpoint()}

    def restore(self, saved: dict):
        self._iteration = saved.get("_iteration", 0)
        self.load_checkpoint(saved.get("payload", {}))

    @property
    def iteration(self) -> int:
        return self._iteration


class FunctionTrainable(Trainable):
    """Wraps ``def train_fn(config)`` that calls ``tune.report(...)``.

    The function runs as a generator: each ``report`` yields one result
    to the runner (reference: function_trainable.py — which uses a
    thread + queue; a generator keeps it deterministic and 1-process).
    """

    _fn: Callable = None  # set by wrap_function subclass

    def setup(self, config):
        self._gen = None          # created lazily so restore() can precede
        self._bridge = None
        self._done = False
        self._restore_payload = None

    def _ensure_gen(self):
        if self._gen is None:
            from ray_tpu.tune import _report_bridge
            self._bridge = _report_bridge.Bridge()
            self._bridge.restore_payload = self._restore_payload
            self._gen = self._bridge.drive(self._fn, self.config)

    def step(self) -> dict:
        if self._done:
            return {**getattr(self, "_last_metrics", {}), "done": True}
        self._ensure_gen()
        try:
            result = next(self._gen)
            self._last_metrics = dict(result)
            return dict(result)
        except StopIteration:
            self._done = True
            # final "done" result carries the last reported metrics so
            # get_best_result sees them (reference: tune marks the last
            # result with done=True rather than emitting an empty one)
            return {**getattr(self, "_last_metrics", {}), "done": True}

    def save_checkpoint(self) -> dict:
        # function trainables checkpoint through tune.report(checkpoint=)
        if self._bridge is not None and self._bridge.latest_checkpoint:
            return self._bridge.latest_checkpoint
        return {}

    def load_checkpoint(self, checkpoint):
        self._restore_payload = checkpoint

    def cleanup(self):
        if self._bridge is not None:
            self._bridge.stop()


def wrap_function(fn: Callable) -> type:
    """Make a Trainable class from a function (reference:
    function_trainable.py wrap_function)."""
    return type(f"func_{getattr(fn, '__name__', 'trainable')}",
                (FunctionTrainable,), {"_fn": staticmethod(fn)})
