"""Experiment-tracking integrations: W&B and MLflow logger callbacks.

Reference capability: python/ray/air/integrations/wandb.py
(WandbLoggerCallback) and mlflow.py (MLflowLoggerCallback) — per-trial
runs in the tracking backend, metrics streamed on every result, final
status on completion.

Both import their client lazily so the framework carries no hard
dependency; constructing a callback without the library raises an
actionable ImportError (matching the reference's behavior).
"""

from __future__ import annotations

from typing import Dict, Optional

from ray_tpu.tune.callback import Callback


class WandbLoggerCallback(Callback):
    """(reference: air/integrations/wandb.py WandbLoggerCallback —
    one wandb run per trial, config logged once, metrics per result)."""

    def __init__(self, project: str, group: Optional[str] = None,
                 api_key: Optional[str] = None, **init_kwargs):
        try:
            import wandb
        except ImportError as e:
            raise ImportError(
                "WandbLoggerCallback requires the `wandb` package; it is "
                "not installed in this environment") from e
        self._wandb = wandb
        self.project = project
        self.group = group
        self.init_kwargs = init_kwargs
        self._runs: Dict[str, object] = {}
        if api_key:
            self._wandb.login(key=api_key)

    def on_trial_start(self, trial) -> None:
        # reinit="create_new": concurrent trials each keep a live run —
        # plain reinit=True finishes the previous trial's run and drops
        # its remaining metric stream
        self._runs[trial.trial_id] = self._wandb.init(
            project=self.project, group=self.group,
            name=trial.trial_id, config=dict(trial.config),
            reinit="create_new", **self.init_kwargs)

    def on_trial_result(self, trial, result: dict) -> None:
        run = self._runs.get(trial.trial_id)
        if run is not None:
            run.log({k: v for k, v in result.items()
                     if isinstance(v, (int, float))})

    def on_trial_complete(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish()

    def on_trial_error(self, trial) -> None:
        run = self._runs.pop(trial.trial_id, None)
        if run is not None:
            run.finish(exit_code=1)

    def on_experiment_end(self, trials: list) -> None:
        for run in self._runs.values():
            run.finish()
        self._runs.clear()


class MLflowLoggerCallback(Callback):
    """(reference: air/integrations/mlflow.py MLflowLoggerCallback —
    one mlflow run per trial under a shared experiment).

    Uses MlflowClient with explicit run ids — the fluent
    ``mlflow.log_metric`` API targets the global *active* run, which
    misroutes metrics as soon as two trials overlap."""

    def __init__(self, experiment_name: str = "ray_tpu",
                 tracking_uri: Optional[str] = None,
                 tags: Optional[dict] = None):
        try:
            from mlflow.tracking import MlflowClient
        except ImportError as e:
            raise ImportError(
                "MLflowLoggerCallback requires the `mlflow` package; it "
                "is not installed in this environment") from e
        self._client = MlflowClient(tracking_uri=tracking_uri)
        exp = self._client.get_experiment_by_name(experiment_name)
        self._experiment_id = (exp.experiment_id if exp is not None
                               else self._client.create_experiment(
                                   experiment_name))
        self.tags = tags or {}
        self._runs: Dict[str, str] = {}   # trial_id -> mlflow run_id

    def on_trial_start(self, trial) -> None:
        run = self._client.create_run(
            self._experiment_id,
            tags={**self.tags, "mlflow.runName": trial.trial_id})
        self._runs[trial.trial_id] = run.info.run_id
        for k, v in trial.config.items():
            try:
                self._client.log_param(run.info.run_id, k, v)
            except Exception:  # noqa: BLE001 - unloggable param type
                pass

    def on_trial_result(self, trial, result: dict) -> None:
        run_id = self._runs.get(trial.trial_id)
        if run_id is None:
            return
        step = int(result.get("training_iteration", 0))
        for k, v in result.items():
            if isinstance(v, (int, float)):
                self._client.log_metric(run_id, k, float(v), step=step)

    def on_trial_complete(self, trial) -> None:
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            self._client.set_terminated(run_id, status="FINISHED")

    def on_trial_error(self, trial) -> None:
        run_id = self._runs.pop(trial.trial_id, None)
        if run_id is not None:
            self._client.set_terminated(run_id, status="FAILED")
