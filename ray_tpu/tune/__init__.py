"""ray_tpu.tune: hyperparameter search (reference capability:
python/ray/tune — SURVEY.md §2.4; build plan §7 M5)."""

from typing import Optional

from ray_tpu.tune import _report_bridge
from ray_tpu.tune.callback import (Callback, CSVLoggerCallback,
                                   JSONLoggerCallback,
                                   TensorBoardLoggerCallback)
from ray_tpu.tune.schedulers import (ASHAScheduler, DistributeResources,
                                     FIFOScheduler, HyperBandScheduler,
                                     MedianStoppingRule, PB2,
                                     PopulationBasedTraining,
                                     ResourceChangingScheduler,
                                     TrialScheduler)
from ray_tpu.tune.search import (BasicVariantGenerator, ConcurrencyLimiter,
                                 Searcher, choice, grid_search, loguniform,
                                 randint, uniform)
from ray_tpu.tune.suggest import GPSearcher, TPESearcher, TuneBOHB
from ray_tpu.tune.trainable import Trainable, FunctionTrainable, wrap_function
from ray_tpu.tune.tuner import ResultGrid, Trial, TuneConfig, Tuner


def report(metrics: dict, *, checkpoint: Optional[dict] = None) -> None:
    """Report one step's metrics from inside a function trainable
    (reference: tune.report / air session.report)."""
    bridge = _report_bridge.current()
    if bridge is None:
        raise RuntimeError("tune.report() called outside a tune trial")
    bridge.report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[dict]:
    """Restore payload for this trial, if the runner restored one."""
    bridge = _report_bridge.current()
    if bridge is None:
        raise RuntimeError("tune.get_checkpoint() outside a tune trial")
    return bridge.get_checkpoint()


__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "Trial", "Trainable",
    "FunctionTrainable", "wrap_function", "report", "get_checkpoint",
    "choice", "uniform", "loguniform", "randint", "grid_search",
    "BasicVariantGenerator", "ConcurrencyLimiter", "Searcher",
    "ASHAScheduler", "DistributeResources", "FIFOScheduler",
    "HyperBandScheduler", "MedianStoppingRule", "PopulationBasedTraining",
    "PB2", "ResourceChangingScheduler", "TrialScheduler",
    "Callback", "CSVLoggerCallback", "JSONLoggerCallback",
    "TensorBoardLoggerCallback",
]

from ray_tpu import usage_stats as _usage_stats
_usage_stats.record_library_usage("tune")
