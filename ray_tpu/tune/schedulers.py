"""Trial schedulers: FIFO, ASHA, PBT.

Reference capability: tune.schedulers (python/ray/tune/schedulers/ —
async_hyperband.py ASHA, pbt.py PBT, fifo.py).  Decisions are made on
every reported result; the runner applies them (stop / pause / exploit).
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_complete(self, trial, result: Optional[dict]):
        pass


class FIFOScheduler(TrialScheduler):
    """Run every trial to completion (reference: schedulers/fifo.py)."""


class ASHAScheduler(TrialScheduler):
    """Asynchronous successive halving
    (reference: tune/schedulers/async_hyperband.py AsyncHyperBandScheduler).

    Rungs at grace_period·rf^k; a trial reaching a rung is stopped unless
    its metric is in the top 1/reduction_factor of results recorded at
    that rung so far (async: no waiting for a full bracket).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: int = 4):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.max_t = max_t
        self.rf = reduction_factor
        self.rungs: list[int] = []
        t = grace_period
        while t < max_t:
            self.rungs.append(t)
            t *= reduction_factor
        # rung milestone -> recorded metric values
        self._recorded: dict[int, list[float]] = defaultdict(list)

    def _better(self, v, cutoff):
        return v <= cutoff if self.mode == "min" else v >= cutoff

    def on_result(self, trial, result) -> str:
        t = result.get("training_iteration", 0)
        v = result.get(self.metric)
        if v is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in reversed(self.rungs):
            if t == rung:
                rec = self._recorded[rung]
                rec.append(float(v))
                k = max(1, len(rec) // self.rf)
                ordered = sorted(rec, reverse=(self.mode == "max"))
                cutoff = ordered[k - 1]
                return CONTINUE if self._better(float(v), cutoff) else STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): at each
    perturbation_interval, bottom-quantile trials exploit (copy weights
    of) a top-quantile trial and explore (perturb) its hyperparams.

    The runner calls ``on_result`` and, when it returns an exploit
    directive via ``pending_exploits``, clones the source trial's
    checkpoint into the target before the next step.
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 5,
                 hyperparam_mutations: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self._scores: dict[str, float] = {}
        self._last_perturb: dict[str, int] = {}
        # trial_id -> (source_trial_id, new_config)
        self.pending_exploits: dict[str, tuple] = {}

    def _quantiles(self):
        items = sorted(self._scores.items(), key=lambda kv: kv[1],
                       reverse=(self.mode == "max"))
        n = len(items)
        k = max(1, int(n * self.quantile))
        top = [tid for tid, _ in items[:k]]
        bottom = [tid for tid, _ in items[-k:]] if n > 1 else []
        return top, bottom

    def _explore(self, config: dict) -> dict:
        from ray_tpu.tune.search import Domain
        out = dict(config)
        for k, spec in self.mutations.items():
            if self.rng.random() < self.resample_p or k not in out:
                if isinstance(spec, Domain):
                    out[k] = spec.sample(self.rng)
                elif isinstance(spec, list):
                    out[k] = self.rng.choice(spec)
                elif callable(spec):
                    out[k] = spec()
            else:
                cur = out[k]
                if isinstance(cur, (int, float)):
                    out[k] = cur * self.rng.choice([0.8, 1.2])
        return out

    def on_result(self, trial, result) -> str:
        v = result.get(self.metric)
        t = result.get("training_iteration", 0)
        if v is None:
            return CONTINUE
        self._scores[trial.trial_id] = float(v)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last >= self.interval and len(self._scores) > 1:
            self._last_perturb[trial.trial_id] = t
            top, bottom = self._quantiles()
            if trial.trial_id in bottom and top:
                src = self.rng.choice(
                    [tid for tid in top if tid != trial.trial_id] or top)
                new_cfg = self._explore(trial.config)
                self.pending_exploits[trial.trial_id] = (src, new_cfg)
        return CONTINUE

    def on_complete(self, trial, result):
        self._scores.pop(trial.trial_id, None)


class PB2(PopulationBasedTraining):
    """PB2: population-based bandits (reference: tune/schedulers/pb2.py,
    Parker-Holder et al. 2020). PBT's exploit step, but explore selects
    new hyperparameters by a GP-UCB bandit fit on observed
    (hyperparams → reward change) data instead of random perturbation —
    sample-efficient for small populations.

    ``hyperparam_bounds`` maps key → (low, high) continuous bounds; the
    GP runs on unit-normalized inputs with an RBF kernel (the same
    dependency-free GP machinery as tune/suggest.py GPSearcher).
    """

    def __init__(self, metric: str = "loss", mode: str = "min",
                 perturbation_interval: int = 5,
                 hyperparam_bounds: Optional[dict] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 1.0,
                 n_candidates: int = 128,
                 length_scale: float = 0.2,
                 seed: Optional[int] = None):
        super().__init__(metric=metric, mode=mode,
                         perturbation_interval=perturbation_interval,
                         hyperparam_mutations={},
                         quantile_fraction=quantile_fraction, seed=seed)
        if not hyperparam_bounds:
            raise ValueError("PB2 requires hyperparam_bounds")
        self.bounds = {k: (float(lo), float(hi))
                       for k, (lo, hi) in hyperparam_bounds.items()}
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        # GP data: rows of (normalized config vector, reward delta)
        self._X: list = []
        self._y: list = []
        self._prev_score: dict[str, float] = {}

    # -- data collection ---------------------------------------------------
    def _norm(self, config: dict) -> list:
        out = []
        for k, (lo, hi) in self.bounds.items():
            v = float(config.get(k, lo))
            out.append((v - lo) / max(hi - lo, 1e-12))
        return out

    def on_result(self, trial, result) -> str:
        v = result.get(self.metric)
        if v is not None:
            prev = self._prev_score.get(trial.trial_id)
            if prev is not None:
                delta = float(v) - prev
                if self.mode == "min":
                    delta = -delta          # larger = better, always
                self._X.append(self._norm(trial.config))
                self._y.append(delta)
                if len(self._X) > 512:      # _explore reads the tail only
                    del self._X[:-512]
                    del self._y[:-512]
            self._prev_score[trial.trial_id] = float(v)
        decision = super().on_result(trial, result)
        if trial.trial_id in self.pending_exploits:
            # the next delta would include the exploit's checkpoint jump
            # — attributing it to the new config would poison the GP
            self._prev_score.pop(trial.trial_id, None)
        return decision

    def on_complete(self, trial, result):
        self._prev_score.pop(trial.trial_id, None)
        super().on_complete(trial, result)

    # -- GP-UCB explore -----------------------------------------------------
    def _explore(self, config: dict) -> dict:
        import numpy as np

        from ray_tpu.tune.suggest import gp_posterior
        out = dict(config)
        if len(self._y) < 4:
            for k, (lo, hi) in self.bounds.items():
                out[k] = lo + self.rng.random() * (hi - lo)
            return out
        X = np.asarray(self._X[-256:])
        y = np.asarray(self._y[-256:])
        cands = np.asarray(
            [[self.rng.random() for _ in self.bounds]
             for _ in range(self.n_candidates)])
        mu, sigma, _ = gp_posterior(X, y, cands, self.length_scale)
        best = cands[int(np.argmax(mu + self.kappa * sigma))]
        for i, (k, (lo, hi)) in enumerate(self.bounds.items()):
            out[k] = lo + float(best[i]) * (hi - lo)
        return out


class MedianStoppingRule(TrialScheduler):
    """Stop trials whose running-average metric falls below the median
    of the running averages of all trials at the same iteration
    (reference: tune/schedulers/median_stopping_rule.py)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 grace_period: int = 1, min_samples_required: int = 3):
        assert mode in ("min", "max")
        self.metric, self.mode = metric, mode
        self.grace_period = grace_period
        self.min_samples = min_samples_required
        # trial_id -> list of (training_iteration, metric value)
        self._results: dict[str, list[tuple[int, float]]] = defaultdict(list)

    def on_result(self, trial, result) -> str:
        v = result.get(self.metric)
        t = result.get("training_iteration", 0)
        if v is None:
            return CONTINUE
        self._results[trial.trial_id].append((int(t), float(v)))
        if t < self.grace_period:
            return CONTINUE
        # compare against other trials' running averages truncated to the
        # same training step, so a young trial is never penalized merely
        # for having fewer (naturally worse) early results
        others = []
        for tid, rs in self._results.items():
            if tid == trial.trial_id:
                continue
            vals = [val for it, val in rs if it <= t]
            if vals:
                others.append(sum(vals) / len(vals))
        if len(others) < self.min_samples:
            return CONTINUE
        import statistics
        median = statistics.median(others)
        mine = [val for _, val in self._results[trial.trial_id]]
        avg = sum(mine) / len(mine)
        worse = avg > median if self.mode == "min" else avg < median
        return STOP if worse else CONTINUE


class HyperBandScheduler(TrialScheduler):
    """Multi-bracket successive halving: trials round-robin over
    num_brackets ASHA ladders with staggered grace periods, trading
    exploration breadth against early-stopping aggressiveness
    (reference: tune/schedulers/hyperband.py HyperBandScheduler; the
    async multi-bracket form of async_hyperband.py brackets>1)."""

    def __init__(self, metric: str = "loss", mode: str = "min",
                 max_t: int = 81, reduction_factor: int = 3,
                 num_brackets: int = 3):
        self.brackets = [
            ASHAScheduler(metric=metric, mode=mode, max_t=max_t,
                          grace_period=max(1, reduction_factor ** s),
                          reduction_factor=reduction_factor)
            for s in range(num_brackets)]
        self._assignment: dict[str, int] = {}
        self._next = 0

    def _bracket_for(self, trial) -> "ASHAScheduler":
        b = self._assignment.get(trial.trial_id)
        if b is None:
            b = self._next % len(self.brackets)
            self._assignment[trial.trial_id] = b
            self._next += 1
        return self.brackets[b]

    def on_result(self, trial, result) -> str:
        return self._bracket_for(trial).on_result(trial, result)

    def on_complete(self, trial, result):
        self._assignment.pop(trial.trial_id, None)


class DistributeResources:
    """Even split of the cluster's CPUs among live trials (reference:
    tune/schedulers/resource_changing_scheduler.py DistributeResources):
    as trials finish, survivors absorb the freed capacity."""

    def __init__(self, max_cpu_per_trial: Optional[float] = None):
        self.max_cpu_per_trial = max_cpu_per_trial

    def __call__(self, trial, result, live_trials: int,
                 total_cpus: float) -> Optional[dict]:
        if live_trials <= 0 or total_cpus <= 0:
            return None
        share = max(1.0, total_cpus // live_trials)
        if self.max_cpu_per_trial is not None:
            share = min(share, self.max_cpu_per_trial)
        return {"CPU": float(share)}


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate trial resources while they train (reference:
    tune/schedulers/resource_changing_scheduler.py).  Wraps a base
    scheduler for stop/continue decisions; after each result the
    allocation function may assign the trial a new resource bundle, and
    the runner restarts the trial's actor from its checkpoint with the
    new allocation.  The trainable sees its current allocation as
    ``config["trial_resources"]`` (the analogue of
    ``tune.get_trial_resources()``)."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        self.alloc = (resources_allocation_function
                      if resources_allocation_function is not None
                      else DistributeResources())
        self.pending_resource_changes: dict[str, dict] = {}
        self._live_trials = 1
        self._total_cpus = 1.0

    def set_context(self, live_trials: int, total_cpus: float) -> None:
        """Called by the runner before each on_result with the cluster
        view the allocator needs."""
        self._live_trials = live_trials
        self._total_cpus = total_cpus

    def on_result(self, trial, result: dict) -> str:
        decision = self.base.on_result(trial, result)
        if decision == CONTINUE:
            new = self.alloc(trial, result, self._live_trials,
                             self._total_cpus)
            # an unset allocation means the 1-CPU default — comparing
            # against {} would churn a pointless rebuild on every
            # trial's first result
            cur = getattr(trial, "resources", None) or {"CPU": 1.0}
            if new and new != cur:
                self.pending_resource_changes[trial.trial_id] = new
        return decision

    def on_complete(self, trial, result: Optional[dict]):
        self.base.on_complete(trial, result)

    @property
    def pending_exploits(self):
        # PBT as the base scheduler keeps working through the wrapper
        return getattr(self.base, "pending_exploits", None)
