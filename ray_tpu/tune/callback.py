"""Tune callbacks + result loggers.

Reference capability: tune/callback.py Callback + tune/logger/
(csv.py CSVLoggerCallback, json.py JSONLoggerCallback, tensorboardx.py)
— per-trial progress files under the run directory, plus user hooks on
trial lifecycle events.
"""

from __future__ import annotations

import csv
import json
import os
from typing import Any, Optional


class Callback:
    """(reference: tune/callback.py Callback hooks subset)

    setup receives restored=True when the experiment resumed from a
    prior run directory, so file-writing callbacks can append instead of
    truncating history."""

    def setup(self, run_dir: str, restored: bool = False):
        pass

    def on_trial_start(self, trial) -> None:
        pass

    def on_trial_result(self, trial, result: dict) -> None:
        pass

    def on_trial_complete(self, trial) -> None:
        pass

    def on_trial_error(self, trial) -> None:
        pass

    def on_experiment_end(self, trials: list) -> None:
        pass


def _scalars(result: dict) -> dict:
    return {k: v for k, v in result.items()
            if isinstance(v, (int, float, str, bool))}


class _PerTrialFileCallback(Callback):
    def __init__(self):
        self._run_dir: Optional[str] = None
        self._restored = False

    def setup(self, run_dir: str, restored: bool = False):
        self._run_dir = run_dir
        self._restored = restored

    def _trial_dir(self, trial) -> str:
        d = os.path.join(self._run_dir or ".", trial.trial_id)
        os.makedirs(d, exist_ok=True)
        return d


class JSONLoggerCallback(_PerTrialFileCallback):
    """result.json: one JSON line per reported result (reference:
    tune/logger/json.py)."""

    def on_trial_start(self, trial):
        with open(os.path.join(self._trial_dir(trial),
                               "params.json"), "w") as f:
            json.dump(_scalars(trial.config), f)

    def on_trial_result(self, trial, result):
        with open(os.path.join(self._trial_dir(trial),
                               "result.json"), "a") as f:
            f.write(json.dumps(_scalars(result)) + "\n")


class CSVLoggerCallback(_PerTrialFileCallback):
    """progress.csv (reference: tune/logger/csv.py).  Columns fixed by
    the first result; later extra keys are dropped (same behavior as the
    reference's CSV logger)."""

    def __init__(self):
        super().__init__()
        self._fields: dict[str, list] = {}

    def on_trial_result(self, trial, result):
        path = os.path.join(self._trial_dir(trial), "progress.csv")
        row = _scalars(result)
        if trial.trial_id not in self._fields:
            if self._restored and os.path.exists(path):
                # restored experiment: keep prior rows, adopt the existing
                # header and append (a fresh 'w' would truncate history).
                # Gated on restored so a NEW run reusing the directory
                # still truncates stale logs.
                with open(path, newline="") as f:
                    header = next(csv.reader(f), None)
                if header:
                    self._fields[trial.trial_id] = header
            if trial.trial_id not in self._fields:
                self._fields[trial.trial_id] = list(row)
                with open(path, "w", newline="") as f:
                    w = csv.DictWriter(f, fieldnames=list(row))
                    w.writeheader()
                    w.writerow(row)
                return
        fields = self._fields[trial.trial_id]
        with open(path, "a", newline="") as f:
            w = csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            w.writerow(row)


class TensorBoardLoggerCallback(_PerTrialFileCallback):
    """TensorBoard event files via torch's SummaryWriter when available;
    silently no-ops otherwise (the environment gates the dependency, as
    with the reference's optional tensorboardX)."""

    def __init__(self):
        super().__init__()
        self._writers: dict[str, Any] = {}

    def _writer(self, trial):
        if trial.trial_id not in self._writers:
            try:
                from torch.utils.tensorboard import SummaryWriter
                self._writers[trial.trial_id] = SummaryWriter(
                    self._trial_dir(trial))
            except Exception:
                self._writers[trial.trial_id] = None
        return self._writers[trial.trial_id]

    def on_trial_result(self, trial, result):
        w = self._writer(trial)
        if w is None:
            return
        step = result.get("training_iteration", 0)
        for k, v in result.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                w.add_scalar(k, v, global_step=step)

    def on_trial_complete(self, trial):
        w = self._writers.pop(trial.trial_id, None)
        if w is not None:
            w.close()
