"""Search spaces + searchers.

Reference capability: tune.search (python/ray/tune/search/ —
basic_variant.py grid/random, ConcurrencyLimiter) and the sample-space
API (tune/search/sample.py).  External-library searchers (hyperopt,
optuna, …) are out of scope by design: the built-in generator covers
grid/random, and the Searcher interface below is the plug point.
"""

from __future__ import annotations

import itertools
import math
import random
from dataclasses import dataclass
from typing import Any, Callable, Iterable, Optional


# -- sample spaces ---------------------------------------------------------

class Domain:
    def sample(self, rng: random.Random):
        raise NotImplementedError


@dataclass
class Choice(Domain):
    values: list

    def sample(self, rng):
        return rng.choice(self.values)


@dataclass
class Uniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


@dataclass
class LogUniform(Domain):
    low: float
    high: float

    def sample(self, rng):
        return math.exp(rng.uniform(math.log(self.low), math.log(self.high)))


@dataclass
class RandInt(Domain):
    low: int
    high: int

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


@dataclass
class GridSearch:
    values: list


def choice(values) -> Choice:
    return Choice(list(values))


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def loguniform(low, high) -> LogUniform:
    return LogUniform(low, high)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def grid_search(values) -> GridSearch:
    return GridSearch(list(values))


# -- searchers -------------------------------------------------------------

class Searcher:
    """Plug point for search algorithms (reference: tune/search/searcher.py).

    suggest(trial_id) -> config dict or None (exhausted);
    on_trial_complete(trial_id, result) feeds outcomes back.
    """

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: dict):
        """Intermediate result hook (budget-aware searchers)."""

    def on_trial_complete(self, trial_id: str, result: Optional[dict]):
        pass

    def set_search_properties(self, metric: Optional[str],
                              mode: Optional[str]) -> None:
        """Adopt the TuneConfig's metric/mode unless the searcher was
        constructed with explicit ones (reference:
        searcher.py set_search_properties)."""


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random draws
    (reference: tune/search/basic_variant.py)."""

    def __init__(self, param_space: dict, num_samples: int = 1,
                 seed: Optional[int] = None):
        self.param_space = param_space
        self.num_samples = num_samples
        self.rng = random.Random(seed)
        self._variants = self._expand()
        self._idx = 0

    def _expand(self) -> list[dict]:
        grid_keys, grid_vals = [], []

        def collect(prefix, space):
            for k, v in space.items():
                key = (*prefix, k)
                if isinstance(v, GridSearch):
                    grid_keys.append(key)
                    grid_vals.append(v.values)
                elif isinstance(v, dict):
                    collect(key, v)

        collect((), self.param_space)
        combos = list(itertools.product(*grid_vals)) if grid_keys else [()]

        out = []
        for _ in range(self.num_samples):
            for combo in combos:
                grid_assign = dict(zip(grid_keys, combo))
                out.append(self._materialize((), self.param_space,
                                             grid_assign))
        return out

    def _materialize(self, prefix, space, grid_assign) -> dict:
        cfg = {}
        for k, v in space.items():
            key = (*prefix, k)
            if isinstance(v, GridSearch):
                cfg[k] = grid_assign[key]
            elif isinstance(v, Domain):
                cfg[k] = v.sample(self.rng)
            elif isinstance(v, dict):
                cfg[k] = self._materialize(key, v, grid_assign)
            elif callable(v) and not isinstance(v, type):
                cfg[k] = v()          # tune.sample_from-style lambda
            else:
                cfg[k] = v
        return cfg

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._idx >= len(self._variants):
            return None
        cfg = self._variants[self._idx]
        self._idx += 1
        return cfg

    @property
    def total_trials(self) -> int:
        return len(self._variants)


class ConcurrencyLimiter(Searcher):
    """(reference: tune/search/concurrency_limiter.py) — caps in-flight
    suggestions; the trial runner also enforces max_concurrent_trials,
    this exists for API parity when wrapping custom searchers."""

    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "PENDING"
        cfg = self.searcher.suggest(trial_id)
        if isinstance(cfg, dict):
            self._live.add(trial_id)
        return cfg

    def on_trial_result(self, trial_id, result):
        self.searcher.on_trial_result(trial_id, result)

    def on_trial_complete(self, trial_id, result):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)

    def set_search_properties(self, metric, mode):
        self.searcher.set_search_properties(metric, mode)


def resolve_config(space_or_cfg: dict, rng: Optional[random.Random] = None):
    """Sample every Domain in a (possibly nested) dict — used by PBT
    explore and one-off config materialization."""
    rng = rng or random.Random()
    out = {}
    for k, v in space_or_cfg.items():
        if isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, GridSearch):
            out[k] = rng.choice(v.values)
        elif isinstance(v, dict):
            out[k] = resolve_config(v, rng)
        else:
            out[k] = v
    return out
