"""Tuner + trial-runner event loop.

Reference capability: tune.Tuner.fit (tuner.py:315) → tune.run
(tune.py:175) → TrialRunner.step (execution/trial_runner.py:272,938) with
RayTrialExecutor running each trial as a remote Trainable actor.

Execution here has two modes:
  * in-process (default): trials step round-robin in the driver — the
    right shape for a single TPU host where trials time-share the chip
    and actor hops would only add pickling;
  * actor mode (``use_actors=True``): each trial is a core-runtime actor
    (ray_tpu.core), giving process isolation and CPU parallelism — the
    analogue of the reference executor, riding our own public actor API
    exactly as the reference rides ray core (SURVEY.md layer rule L7).
"""

from __future__ import annotations

import os
import pickle
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Union

from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import RunConfig
from ray_tpu.train.result import Result
from ray_tpu.tune import schedulers as _sched
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.schedulers import (CONTINUE, STOP, FIFOScheduler,
                                     TrialScheduler)
from ray_tpu.tune.trainable import Trainable, wrap_function


@dataclass
class TuneConfig:
    metric: str = "loss"
    mode: str = "min"
    num_samples: int = 1
    max_concurrent_trials: Optional[int] = None
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    use_actors: bool = False
    seed: Optional[int] = None


@dataclass
class Trial:
    trial_id: str
    config: dict
    status: str = "PENDING"      # PENDING/RUNNING/TERMINATED/ERROR
    last_result: dict = field(default_factory=dict)
    history: list = field(default_factory=list)
    error: Optional[str] = None
    runner: Any = None           # Trainable or actor handle
    checkpoint: Optional[dict] = None
    # current allocation (ResourceChangingScheduler updates it mid-run)
    resources: Optional[dict] = None

    @property
    def iterations(self) -> int:
        return self.last_result.get("training_iteration", 0)


class ResultGrid:
    """(reference: tune/result_grid.py)"""

    def __init__(self, trials: list[Trial], metric: str, mode: str,
                 path: str):
        self.trials = trials
        self.metric, self.mode = metric, mode
        self.path = path

    def __len__(self):
        return len(self.trials)

    def __getitem__(self, i) -> Result:
        t = self.trials[i]
        return Result(metrics=t.last_result, path=self.path,
                      metrics_history=t.history,
                      error=RuntimeError(t.error) if t.error else None)

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self.metric
        mode = mode or self.mode
        scored = [t for t in self.trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (min if mode == "min" else max)(
            scored, key=lambda t: t.last_result[metric])
        return Result(metrics=best.last_result, path=self.path,
                      metrics_history=best.history)

    @property
    def errors(self):
        return [t.error for t in self.trials if t.error]


class _ActorTrialShim:
    """Runs a Trainable inside a core-runtime actor."""

    def __init__(self, trainable_cls_bytes: bytes, config: dict):
        cls = pickle.loads(trainable_cls_bytes)
        self._t = cls(config)

    def train(self):
        return self._t.train()

    def save(self):
        return self._t.save()

    def restore(self, saved):
        return self._t.restore(saved)

    def cleanup(self):
        self._t.cleanup()


class Tuner:
    """(reference: tune/tuner.py Tuner.fit:315)"""

    def __init__(self, trainable: Union[Callable, type],
                 *, param_space: Optional[dict] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None):
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig(name="tune")
        if isinstance(trainable, type) and issubclass(trainable, Trainable):
            self.trainable_cls = trainable
        elif callable(trainable):
            self.trainable_cls = wrap_function(trainable)
        else:
            raise TypeError("trainable must be a function or Trainable")
        self.param_space = param_space or {}
        self._restored: list[Trial] = []   # from Tuner.restore

    # -- experiment-state checkpointing (reference: TrialRunner
    #    experiment checkpoint + Tuner.restore, tuner.py/trial_runner.py)

    @staticmethod
    def _experiment_state_path(run_dir: str) -> str:
        return os.path.join(run_dir, "experiment_state.pkl")

    def _save_experiment_state(self, run_dir: str, trials: list,
                               searcher=None) -> None:
        import cloudpickle
        state = [{"trial_id": t.trial_id, "config": t.config,
                  "status": t.status, "last_result": t.last_result,
                  "history": t.history, "error": t.error,
                  "checkpoint": t.checkpoint,
                  "resources": t.resources} for t in trials]
        payload = {"trials": state, "param_space": self.param_space}
        # searcher + configs ride along so restore continues the SAME
        # experiment: remaining suggestions, metric/mode, stop criteria,
        # schedulers, callbacks.  Unpicklable user objects degrade to
        # defaults rather than failing the checkpoint.
        for key, obj in (("searcher", searcher),
                         ("tune_config", self.tune_config),
                         ("run_config", self.run_config)):
            try:
                payload[key] = cloudpickle.dumps(obj)
            except Exception:
                payload[key] = None
        tmp = self._experiment_state_path(run_dir) + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(payload, f)
        os.replace(tmp, self._experiment_state_path(run_dir))

    @classmethod
    def restore(cls, path: str, trainable) -> "Tuner":
        """Resume an interrupted experiment from its run directory:
        completed trials keep their results, unfinished ones re-run from
        their last saved checkpoint, and the restored searcher continues
        suggesting any configs the interrupted run never reached."""
        import cloudpickle
        with open(cls._experiment_state_path(path), "rb") as f:
            state = pickle.load(f)

        def load(key):
            raw = state.get(key)
            try:
                return cloudpickle.loads(raw) if raw is not None else None
            except Exception:
                return None

        tune_config = load("tune_config") or TuneConfig()
        run_config = load("run_config") or RunConfig(
            name=os.path.basename(path.rstrip(os.sep)),
            storage_path=os.path.dirname(path.rstrip(os.sep)) or None)
        searcher = load("searcher")
        if searcher is not None:
            tune_config.search_alg = searcher
        tuner = cls(trainable, param_space=state["param_space"],
                    tune_config=tune_config, run_config=run_config)
        for ts in state["trials"]:
            t = Trial(trial_id=ts["trial_id"], config=ts["config"],
                      status=ts["status"], last_result=ts["last_result"],
                      history=ts["history"], error=ts["error"],
                      checkpoint=ts["checkpoint"],
                      resources=ts.get("resources"))
            tuner._restored.append(t)
        return tuner

    # -- executor helpers --------------------------------------------------

    def _make_runner(self, trial: Trial):
        cfg = dict(trial.config)
        if trial.resources:
            # the trainable reads its live allocation here (analogue of
            # tune.get_trial_resources) and can resize accordingly
            cfg["trial_resources"] = dict(trial.resources)
        if self.tune_config.use_actors:
            import cloudpickle
            import ray_tpu
            cls_bytes = cloudpickle.dumps(self.trainable_cls)
            Actor = ray_tpu.remote(_ActorTrialShim)
            if trial.resources:
                opts = {}
                if "CPU" in trial.resources:
                    opts["num_cpus"] = trial.resources["CPU"]
                extra = {k: v for k, v in trial.resources.items()
                         if k not in ("CPU",)}
                if extra:
                    opts["resources"] = extra
                Actor = Actor.options(**opts)
            trial.runner = Actor.remote(cls_bytes, cfg)
            trial._is_actor = True
        else:
            trial.runner = self.trainable_cls(cfg)
            trial._is_actor = False
        if trial.checkpoint is not None:
            self._runner_call(trial, "restore", trial.checkpoint)

    def _runner_call(self, trial: Trial, method: str, *args):
        if getattr(trial, "_is_actor", False):
            import ray_tpu
            return ray_tpu.get(
                getattr(trial.runner, method).remote(*args), timeout=600)
        return getattr(trial.runner, method)(*args)

    # -- the event loop ----------------------------------------------------

    def fit(self) -> ResultGrid:
        tc = self.tune_config
        run_dir = self.run_config.resolved_storage_path()
        os.makedirs(run_dir, exist_ok=True)

        searcher = tc.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=tc.num_samples, seed=tc.seed)
        # sync optimization target into the searcher (reference:
        # set_search_properties) — a silent metric mismatch would leave a
        # model-based searcher blind or optimizing the wrong direction
        if hasattr(searcher, "set_search_properties"):
            searcher.set_search_properties(tc.metric, tc.mode)
        scheduler = tc.scheduler or FIFOScheduler()
        callbacks = list(self.run_config.callbacks)
        stop_criteria = self.run_config.stop or {}
        for cb in callbacks:
            import inspect
            try:
                params = inspect.signature(cb.setup).parameters
                takes_restored = ("restored" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()))
            except (TypeError, ValueError):
                takes_restored = False
            if takes_restored:
                cb.setup(run_dir, restored=bool(self._restored))
            else:  # user callback with the pre-r2 signature
                cb.setup(run_dir)

        trials: list[Trial] = []
        live: list[Trial] = []
        # resume: completed trials keep results, unfinished re-queue
        requeued: list[Trial] = []
        for t in self._restored:
            if t.status == "TERMINATED":
                trials.append(t)
            else:
                t.status = "PENDING"
                t.error = None
                requeued.append(t)
        # the restored searcher (if any) continues past already-suggested
        # configs; a restore without searcher state falls back to the
        # fresh variant generator and skips its first len(restored)
        # suggestions — NOT declaring the search exhausted (which would
        # silently drop the remaining num_samples trials).  Count-based
        # skipping equals config-equality skipping for deterministic
        # suggestion sequences (grid, seeded random) and is the correct
        # semantics for seedless random search, where draws are
        # exchangeable and re-matching exact configs is impossible.
        skip_count = (len(self._restored)
                      if self._restored and tc.search_alg is None else 0)
        exhausted = False
        n = len(self._restored)
        max_live = tc.max_concurrent_trials or float("inf")

        # round-robin stepping (reference TrialRunner.step:938 analogue);
        # trials are suggested LAZILY so capacity-limited searchers
        # (ConcurrencyLimiter) get asked again as slots free up
        while True:
            made_progress = False
            while len(live) < max_live and (requeued or not exhausted):
                if requeued:
                    t = requeued.pop(0)
                else:
                    tid = f"trial_{n:05d}"
                    cfg = searcher.suggest(tid)
                    if cfg is None:
                        exhausted = True
                        break
                    if cfg == "PENDING":  # searcher at capacity; retry later
                        break
                    if skip_count > 0:
                        # this suggestion slot already ran before the
                        # interruption
                        skip_count -= 1
                        continue
                    t = Trial(trial_id=tid, config=cfg)
                    n += 1
                made_progress = True
                trials.append(t)
                try:
                    self._make_runner(t)
                    t.status = "RUNNING"
                    live.append(t)
                    for cb in callbacks:
                        cb.on_trial_start(t)
                except Exception:
                    t.status = "ERROR"
                    t.error = traceback.format_exc()
                    scheduler.on_complete(t, None)
                    searcher.on_trial_complete(t.trial_id, None)
                    for cb in callbacks:
                        cb.on_trial_error(t)
            if not live:
                if exhausted or not made_progress:
                    break   # done, or searcher wedged with nothing live
                continue
            total_cpus = 1.0
            if hasattr(scheduler, "set_context"):
                # once per pass, not per result — the cluster view
                # doesn't change between trials within one sweep
                try:
                    import ray_tpu
                    total_cpus = ray_tpu.cluster_resources().get("CPU", 1.0)
                except Exception:
                    pass
            for t in list(live):
                try:
                    result = self._runner_call(t, "train")
                except Exception:
                    t.status = "ERROR"
                    t.error = traceback.format_exc()
                    live.remove(t)
                    scheduler.on_complete(t, None)
                    searcher.on_trial_complete(t.trial_id, None)
                    for cb in callbacks:
                        cb.on_trial_error(t)
                    self._save_experiment_state(run_dir, trials, searcher)
                    continue
                t.last_result = result
                t.history.append(result)
                # budget-aware searchers (BOHB) learn from intermediate
                # results at their training budget
                searcher.on_trial_result(t.trial_id, result)
                for cb in callbacks:
                    cb.on_trial_result(t, result)
                freq = self.run_config.checkpoint_config.checkpoint_frequency
                if freq and t.iterations % freq == 0:
                    # periodic trial checkpoint → resumable experiment
                    t.checkpoint = self._runner_call(t, "save")
                    self._save_experiment_state(run_dir, trials, searcher)
                done = result.get("done", False)
                for k, v in stop_criteria.items():
                    if k in result and result[k] >= v:
                        done = True
                if hasattr(scheduler, "set_context"):
                    scheduler.set_context(len(live), total_cpus)
                decision = scheduler.on_result(t, result)
                # resource reallocation: restart the runner from its
                # checkpoint with the new bundle (reference:
                # resource_changing_scheduler.py apply path).  Skipped
                # when the trial is ending anyway or a PBT exploit will
                # rebuild the runner this same iteration.
                realloc = getattr(scheduler, "pending_resource_changes",
                                  None)
                exploit_pending = t.trial_id in (
                    getattr(scheduler, "pending_exploits", None) or {})
                if (realloc and t.trial_id in realloc
                        and decision != STOP and not done
                        and not exploit_pending):
                    new_res = realloc.pop(t.trial_id)
                    try:
                        saved = self._runner_call(t, "save")
                        self._runner_call(t, "cleanup")
                        t.checkpoint = saved
                        t.resources = new_res
                        self._make_runner(t)
                    except Exception:
                        # a failed rebuild fails THIS trial, not fit()
                        t.status = "ERROR"
                        t.error = traceback.format_exc()
                        live.remove(t)
                        scheduler.on_complete(t, t.last_result)
                        searcher.on_trial_complete(t.trial_id,
                                                   t.last_result)
                        for cb in callbacks:
                            cb.on_trial_error(t)
                        continue
                # PBT exploit: clone src weights + new config
                exploits = getattr(scheduler, "pending_exploits", None)
                if exploits and t.trial_id in exploits:
                    src_id, new_cfg = exploits.pop(t.trial_id)
                    src = next(x for x in trials if x.trial_id == src_id)
                    if src.runner is not None:
                        saved = self._runner_call(src, "save")
                        t.config = new_cfg
                        self._runner_call(t, "cleanup")
                        t.checkpoint = saved
                        self._make_runner(t)
                if done or decision == STOP:
                    t.status = "TERMINATED"
                    live.remove(t)
                    t.checkpoint = self._runner_call(t, "save")
                    self._runner_call(t, "cleanup")
                    scheduler.on_complete(t, t.last_result)
                    searcher.on_trial_complete(t.trial_id, t.last_result)
                    for cb in callbacks:
                        cb.on_trial_complete(t)
                    self._save_experiment_state(run_dir, trials, searcher)
        self._save_experiment_state(run_dir, trials, searcher)
        for cb in callbacks:
            cb.on_experiment_end(trials)
        return ResultGrid(trials, tc.metric, tc.mode, run_dir)
