"""Bridge between imperative ``tune.report(...)`` calls inside a user
function and the stepwise Trainable interface the trial runner drives.

The function runs in a worker thread; each report() hands one result to
the runner and blocks until the runner asks for the next step — so a
function trainable behaves exactly like a class trainable from the
scheduler's point of view (reference: tune/trainable/function_trainable.py
uses the same thread+queue handoff, _RunnerThread)."""

from __future__ import annotations

import threading
from typing import Any, Callable, Optional

_bridges: dict[int, "Bridge"] = {}   # fn-thread ident -> bridge


class StopTrial(BaseException):
    """Raised inside the fn thread when the trial is stopped early."""


class Bridge:
    def __init__(self):
        self._cond = threading.Condition()
        self._result: Optional[dict] = None
        self._consumed = True
        self._stop = False
        self._finished = False
        self._error: Optional[BaseException] = None
        self.latest_checkpoint: Optional[dict] = None
        self.restore_payload: Optional[dict] = None

    # -- called from the fn thread ----------------------------------------

    def report(self, metrics: dict, *, checkpoint: Optional[dict] = None):
        with self._cond:
            if self._stop:
                raise StopTrial()
            if checkpoint is not None:
                self.latest_checkpoint = checkpoint
            self._result = dict(metrics)
            self._consumed = False
            self._cond.notify_all()
            while not self._consumed and not self._stop:
                self._cond.wait()
            if self._stop:
                raise StopTrial()

    def get_checkpoint(self) -> Optional[dict]:
        return self.restore_payload

    # -- called from the runner -------------------------------------------

    def drive(self, fn: Callable, config: dict):
        def run():
            _bridges[threading.get_ident()] = self
            try:
                fn(config)
            except StopTrial:
                pass
            except BaseException as e:
                self._error = e
            finally:
                _bridges.pop(threading.get_ident(), None)
                with self._cond:
                    self._finished = True
                    self._cond.notify_all()

        thread = threading.Thread(target=run, daemon=True)
        thread.start()
        while True:
            with self._cond:
                while self._consumed and not self._finished:
                    self._cond.wait()
                if not self._consumed:
                    result = self._result
                    self._consumed = True
                    self._cond.notify_all()
                else:  # finished with no pending result
                    if self._error is not None:
                        raise self._error
                    return
            yield result

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()


def current() -> Optional[Bridge]:
    return _bridges.get(threading.get_ident())


def push(bridge: Bridge) -> Bridge:   # kept for API symmetry
    return bridge


def pop(token):
    pass
