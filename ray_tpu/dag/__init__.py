"""ray_tpu.dag: lazy task/actor DAGs.

Reference capability: python/ray/dag/ (DAGNode dag_node.py:23,
FunctionNode/ClassNode/InputNode, dag.execute()) — the base layer for
Serve graphs and Workflow.  ``fn.bind(*args)`` builds nodes; execute()
topologically evaluates, submitting bound remote functions through the
core runtime when it is initialized (else inline).
"""

from ray_tpu.dag.dag_node import (ClassNode, DAGNode, FunctionNode,
                                  InputNode, MultiOutputNode)

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "InputNode",
           "MultiOutputNode"]
