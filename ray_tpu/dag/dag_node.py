"""DAG nodes (reference: python/ray/dag/dag_node.py:23 DAGNode,
function_node.py, input_node.py; executed bottom-up like
dag.execute())."""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

_ids = itertools.count()


class DAGNode:
    """A lazily-bound computation node."""

    def __init__(self, args: tuple = (), kwargs: Optional[dict] = None):
        self._bound_args = args
        self._bound_kwargs = kwargs or {}
        self._id = next(_ids)

    # -- graph traversal ---------------------------------------------------

    def _children(self):
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                yield a

    def _apply_recursive(self, fn, memo: dict):
        if self._id in memo:
            return memo[self._id]
        args = tuple(a._apply_recursive(fn, memo) if isinstance(a, DAGNode)
                     else a for a in self._bound_args)
        kwargs = {k: (v._apply_recursive(fn, memo) if isinstance(v, DAGNode)
                      else v) for k, v in self._bound_kwargs.items()}
        out = fn(self, args, kwargs)
        memo[self._id] = out
        return out

    # -- execution ---------------------------------------------------------

    def execute(self, *input_args, _resolve: bool = True, **input_kwargs):
        """Evaluate the DAG (reference: dag_node.py execute).  Uses the
        core runtime for FunctionNodes when initialized; ObjectRefs flow
        between nodes so the scheduler sees real dependencies."""
        import ray_tpu
        use_runtime = ray_tpu.is_initialized()
        memo: dict = {}

        def run(node, args, kwargs):
            return node._execute_impl(args, kwargs, input_args,
                                      input_kwargs, use_runtime)

        out = self._apply_recursive(run, memo)
        if _resolve and use_runtime:
            from ray_tpu.core.object_ref import ObjectRef

            def resolve(x):
                if isinstance(x, ObjectRef):
                    return ray_tpu.get(x, timeout=300)
                if isinstance(x, (list, tuple)):
                    return type(x)(resolve(v) for v in x)
                return x

            out = resolve(out)
        return out

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for execute()-time input (reference: input_node.py).
    Supports `with InputNode() as x:` for API parity."""

    def __init__(self, index: int = 0):
        super().__init__()
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        return input_args[self.index]


class FunctionNode(DAGNode):
    def __init__(self, fn: Callable, args, kwargs,
                 options: Optional[dict] = None):
        super().__init__(args, kwargs)
        self._fn = fn
        self._options = options or {}

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        if use_runtime:
            import ray_tpu
            rf = ray_tpu.remote(self._fn)
            if self._options:
                rf = rf.options(**self._options)
            return rf.remote(*args, **kwargs)
        # inline: resolve nothing, just call

        def deref(x):
            return x

        return self._fn(*[deref(a) for a in args],
                        **{k: deref(v) for k, v in kwargs.items()})


class ClassNode(DAGNode):
    """A bound actor-constructor node; method .bind on its result gives
    ClassMethodNodes (reference: class_node.py)."""

    def __init__(self, cls: type, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = cls
        self._instance = None

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        if self._instance is None:
            if use_runtime:
                import ray_tpu
                self._instance = ray_tpu.remote(self._cls).remote(
                    *args, **kwargs)
            else:
                self._instance = self._cls(*args, **kwargs)
        return self._instance

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__((class_node, *args), kwargs)
        self._method = method

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        instance, *rest = args
        if use_runtime:
            return getattr(instance, self._method).remote(*rest, **kwargs)
        return getattr(instance, self._method)(*rest, **kwargs)


class MultiOutputNode(DAGNode):
    """Bundle several leaves (reference: output_node.py)."""

    def __init__(self, outputs: list):
        super().__init__(tuple(outputs))

    def _execute_impl(self, args, kwargs, input_args, input_kwargs,
                      use_runtime):
        return list(args)


def bind_function(fn: Callable, *args, _options=None, **kwargs):
    return FunctionNode(fn, args, kwargs, options=_options)


def bind_class(cls: type, *args, **kwargs) -> ClassNode:
    return ClassNode(cls, args, kwargs)
