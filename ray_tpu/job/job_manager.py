"""Job supervisor actor + submission client.

Reference: dashboard/modules/job/job_manager.py — JobSupervisor actor
per job (:490 submit_job → supervisor actor → subprocess driver),
status persisted to the GCS KV (job_info_storage_client).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass
from typing import Optional

# terminal + live states (reference: JobStatus enum, common.py)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


class JobStatus:
    PENDING, RUNNING, SUCCEEDED, FAILED, STOPPED = (
        PENDING, RUNNING, SUCCEEDED, FAILED, STOPPED)
    TERMINAL = {SUCCEEDED, FAILED, STOPPED}


@dataclass
class JobInfo:
    job_id: str
    status: str
    entrypoint: str
    message: str = ""
    start_time: float = 0.0
    end_time: float = 0.0
    metadata: Optional[dict] = None


def _kv_key(job_id: str) -> bytes:
    return f"job:{job_id}".encode()


def _logs_key(job_id: str) -> bytes:
    return f"job:{job_id}:logs".encode()


_MAX_LOG_BYTES = 4 * 1024 * 1024


class _JobSupervisor:
    """One actor per job (reference: JobSupervisor, job_manager.py:161).
    Runs in its own worker process; the entrypoint is a subprocess so a
    crashing job can never take the supervisor down with it."""

    def __init__(self, job_id: str, entrypoint: str,
                 runtime_env: Optional[dict], node_address: str,
                 metadata: Optional[dict]):
        self.job_id = job_id
        self.entrypoint = entrypoint
        self.runtime_env = runtime_env or {}
        self.node_address = node_address
        self.metadata = metadata or {}
        self._proc: Optional[subprocess.Popen] = None
        self._stopped = False
        self._log = bytearray()   # in-place append: chatty jobs must
        #                           not pay quadratic copying
        self._set_status(PENDING)

    # -- kv state -----------------------------------------------------------

    def _client(self):
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().client

    def _set_status(self, status: str, message: str = "",
                    start: Optional[float] = None,
                    end: Optional[float] = None) -> None:
        cur = {}
        raw = self._client().kv_get(_kv_key(self.job_id))
        if raw:
            cur = json.loads(raw)
        cur.update({"job_id": self.job_id, "status": status,
                    "entrypoint": self.entrypoint,
                    "metadata": self.metadata})
        if message:
            cur["message"] = message
        if start is not None:
            cur["start_time"] = start
        if end is not None:
            cur["end_time"] = end
        self._client().kv_put(_kv_key(self.job_id),
                              json.dumps(cur).encode())

    def _flush_logs(self) -> None:
        self._client().kv_put(_logs_key(self.job_id),
                              bytes(self._log[-_MAX_LOG_BYTES:]))

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> str:
        """Blocks until the entrypoint exits; returns terminal status."""
        from ray_tpu.runtime_env import applied_env
        cwd = None
        with applied_env({k: v for k, v in self.runtime_env.items()
                          if k != "env_vars"}, self._client()) as ae:
            # snapshot INSIDE applied_env: conda prepends PATH and sets
            # CONDA_PREFIX on os.environ — the entrypoint subprocess
            # must see the activated environment too
            env = dict(os.environ)
            env.update(self.runtime_env.get("env_vars") or {})
            # the job's own driver connects to the SAME cluster
            env["RAY_TPU_ADDRESS"] = self.node_address
            if self.runtime_env.get("working_dir"):
                cwd = os.getcwd()   # applied_env chdir'd into the pkg
            if ae.paths:
                # materialized working_dir/py_modules must be importable
                # in the ENTRYPOINT subprocess too
                env["PYTHONPATH"] = os.pathsep.join(
                    ae.paths + [p for p in
                                env.get("PYTHONPATH", "").split(os.pathsep)
                                if p])
            if self._stopped:   # stop() raced submission: cancel cleanly
                self._set_status(STOPPED, message="stopped before start",
                                 end=time.time())
                return STOPPED
            self._set_status(RUNNING, start=time.time())
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True, cwd=cwd, env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                start_new_session=True)
            assert self._proc.stdout is not None
            last_flush = time.monotonic()
            for line in self._proc.stdout:
                self._log += line
                if len(self._log) > 2 * _MAX_LOG_BYTES:
                    self._log = self._log[-_MAX_LOG_BYTES:]
                if time.monotonic() - last_flush > 1.0:
                    self._flush_logs()
                    last_flush = time.monotonic()
            rc = self._proc.wait()
        self._flush_logs()
        if self._stopped:
            status = STOPPED
        else:
            status = SUCCEEDED if rc == 0 else FAILED
        self._set_status(status, message=f"exit code {rc}",
                         end=time.time())
        return status

    def stop(self) -> bool:
        """True if the job was killed OR will be cancelled before it
        starts; False only when it already finished."""
        already_done = (self._proc is not None
                        and self._proc.poll() is not None)
        if already_done:
            return False
        self._stopped = True
        if self._proc is not None:
            import signal
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass
        return True

    def logs_tail(self, nbytes: int = 65536) -> bytes:
        return bytes(self._log[-nbytes:])



class JobSubmissionClient:
    """Submit/inspect jobs against a running cluster node
    (reference: dashboard/modules/job/sdk.py JobSubmissionClient)."""

    def __init__(self, address: Optional[str] = None):
        import ray_tpu
        if not ray_tpu.is_initialized():
            if address is None:
                address = os.environ.get("RAY_TPU_ADDRESS")
            ray_tpu.init(address=address)
        self._rt = ray_tpu.get_runtime()
        self._address = address or self._rt.client.address
        self._supervisors: dict[str, object] = {}

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   job_id: Optional[str] = None,
                   metadata: Optional[dict] = None) -> str:
        import ray_tpu
        from ray_tpu.runtime_env import (package_directory, upload_package,
                                         validate)
        job_id = job_id or f"raytpu_job_{uuid.uuid4().hex[:10]}"
        runtime_env = validate(dict(runtime_env or {}))
        wd = runtime_env.get("working_dir")
        if wd and os.path.isdir(wd):
            # content-addressed upload; workers materialize from the KV
            runtime_env["working_dir"] = upload_package(
                self._rt.client, package_directory(wd))
        mods = runtime_env.get("py_modules")
        if mods:
            runtime_env["py_modules"] = [
                upload_package(self._rt.client, package_directory(m))
                if os.path.isdir(m) else m
                for m in ([mods] if isinstance(mods, str) else mods)]
        # the PENDING record lands BEFORE the (async) supervisor spawn so
        # status queries never race actor creation (reference: the job
        # manager writes JobInfo first, then starts the supervisor)
        self._rt.client.kv_put(
            _kv_key(job_id),
            json.dumps({"job_id": job_id, "status": PENDING,
                        "entrypoint": entrypoint,
                        "metadata": metadata or {}}).encode())
        Supervisor = ray_tpu.remote(_JobSupervisor).options(
            name=f"_job_supervisor:{job_id}", max_concurrency=4)
        sup = Supervisor.remote(job_id, entrypoint, runtime_env,
                                self._address, metadata)
        self._supervisors[job_id] = sup
        sup.run.remote()   # fire and track via KV
        return job_id

    def _info(self, job_id: str) -> JobInfo:
        raw = self._rt.client.kv_get(_kv_key(job_id))
        if raw is None:
            raise ValueError(f"no such job {job_id!r}")
        d = json.loads(raw)
        return JobInfo(job_id=d["job_id"], status=d["status"],
                       entrypoint=d.get("entrypoint", ""),
                       message=d.get("message", ""),
                       start_time=d.get("start_time", 0.0),
                       end_time=d.get("end_time", 0.0),
                       metadata=d.get("metadata"))

    def get_job_status(self, job_id: str) -> str:
        return self._info(job_id).status

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        raw = self._rt.client.kv_get(_logs_key(job_id))
        return (raw or b"").decode("utf-8", "replace")

    def list_jobs(self) -> list[JobInfo]:
        out = []
        for key in self._rt.client.kv_keys(prefix=b"job:"):
            name = key.decode()
            if name.endswith(":logs"):
                continue
            out.append(self._info(name.split(":", 1)[1]))
        return out

    def stop_job(self, job_id: str) -> bool:
        import ray_tpu
        sup = self._supervisors.get(job_id)
        if sup is None:
            try:
                sup = ray_tpu.get_actor(f"_job_supervisor:{job_id}")
            except Exception:
                return False
        try:
            return ray_tpu.get(sup.stop.remote(), timeout=30)
        except Exception:
            return False

    def wait_until_finished(self, job_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {job_id} not finished in {timeout}s")
