"""Job submission: run an entrypoint command ON the cluster.

Reference capability: the job submission stack
(reference: dashboard/modules/job/job_manager.py:490 JobManager +
python/ray/dashboard/modules/job/sdk.py JobSubmissionClient + the
`ray job` CLI).  Shape here: a job is a supervisor ACTOR that
materializes the job's runtime env, runs the entrypoint as a
subprocess, streams its output to a log buffer, and records status in
the cluster KV store — so any later client (or the CLI) can query
status/logs after the submitter disconnected.
"""

from ray_tpu.job.job_manager import (JobInfo, JobStatus,
                                     JobSubmissionClient)

__all__ = ["JobSubmissionClient", "JobStatus", "JobInfo"]
