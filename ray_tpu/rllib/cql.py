"""CQL: Conservative Q-Learning (offline, discrete actions).

Reference capability: rllib/algorithms/cql/ (cql.py,
cql_torch_policy.py) — offline RL that augments the TD loss with a
conservative regularizer penalizing Q-values of actions not in the
dataset: L = TD + α_cql·E_s[logsumexp_a Q(s,a) − Q(s, a_data)].

Discrete-action variant over the DQN Q-network; the dataset comes from
offline.JsonReader with (obs, actions, rewards, dones, next_obs)
columns.  The whole update (double-Q TD target + CQL penalty) is one
jitted program.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.dqn import init_q_params, q_values
from ray_tpu.rllib.offline import JsonReader
from ray_tpu.rllib.sample_batch import SampleBatch


@dataclass
class CQLConfig(AlgorithmConfig):
    input_path: str = ""             # offline data dir (JsonReader)
    cql_alpha: float = 1.0           # conservative penalty weight
    batch_size: int = 256
    grad_steps_per_iter: int = 100
    target_update_freq: int = 500    # in grad steps
    tau: float = 1.0                 # 1.0 = hard target sync
    gamma: float = 0.99
    lr: float = 3e-4
    double_q: bool = True

    def build(self, algo_cls=None) -> "CQL":
        return CQL({"_config": self})


def make_cql_update(cfg: CQLConfig, tx):
    @jax.jit
    def update(params, target_params, opt_state, batch):
        obs, actions = batch["obs"], batch["actions"]
        rewards, dones, next_obs = (batch["rewards"], batch["dones"],
                                    batch["next_obs"])
        q_next_t = q_values(target_params, next_obs)
        if cfg.double_q:
            sel = jnp.argmax(q_values(params, next_obs), axis=-1)
        else:
            sel = jnp.argmax(q_next_t, axis=-1)
        boot = jnp.take_along_axis(q_next_t, sel[:, None], 1)[:, 0]
        target = rewards + cfg.gamma * (1.0 - dones) * boot

        def loss_fn(p):
            q_all = q_values(p, obs)
            q_data = jnp.take_along_axis(q_all, actions[:, None], 1)[:, 0]
            td = jnp.mean((q_data - jax.lax.stop_gradient(target)) ** 2)
            # conservative gap: push down OOD actions, up dataset actions
            gap = jnp.mean(jax.scipy.special.logsumexp(q_all, axis=-1)
                           - q_data)
            return td + cfg.cql_alpha * gap, (td, gap)

        (loss, (td, gap)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss, td, gap

    return update


class CQL(Algorithm):
    _default_config = CQLConfig

    def _build(self):
        cfg = self.config
        if not cfg.input_path:
            raise ValueError("CQL requires config.input_path offline data")
        self.data = JsonReader(cfg.input_path).read_all()
        self.obs_dim = int(np.asarray(self.data["obs"]).shape[1])
        self.num_actions = int(np.asarray(self.data["actions"]).max()) + 1
        self.params = init_q_params(self.obs_dim, self.num_actions,
                                    cfg.hiddens, False,
                                    jax.random.PRNGKey(cfg.seed))
        self.target_params = self.params
        self.tx = optax.adam(cfg.lr)
        self.opt_state = self.tx.init(self.params)
        self._update = make_cql_update(cfg, self.tx)
        self._rng = np.random.default_rng(cfg.seed)
        self._grad_steps = 0

    def training_step(self) -> dict:
        cfg = self.config
        n = len(np.asarray(self.data["obs"]))
        losses, tds, gaps = [], [], []
        for _ in range(cfg.grad_steps_per_iter):
            idx = self._rng.integers(0, n, cfg.batch_size)
            jb = {k: jnp.asarray(np.asarray(self.data[k])[idx])
                  for k in ("obs", "actions", "rewards", "dones",
                            "next_obs")}
            jb["actions"] = jb["actions"].astype(jnp.int32)
            self.params, self.opt_state, loss, td, gap = self._update(
                self.params, self.target_params, self.opt_state, jb)
            losses.append(float(loss))
            tds.append(float(td))
            gaps.append(float(gap))
            self._grad_steps += 1
            if self._grad_steps % cfg.target_update_freq == 0:
                self.target_params = jax.tree.map(
                    lambda t, p: (1 - cfg.tau) * t + cfg.tau * p,
                    self.target_params, self.params)
        self._timesteps += cfg.grad_steps_per_iter
        return {"steps_this_iter": cfg.grad_steps_per_iter,
                "loss": float(np.mean(losses)),
                "td_loss": float(np.mean(tds)),
                "cql_gap": float(np.mean(gaps))}

    def compute_action(self, obs: np.ndarray) -> int:
        q = q_values(self.params, jnp.asarray(obs, jnp.float32)[None])
        return int(jnp.argmax(q[0]))

    def save_checkpoint(self) -> dict:
        return {"params": jax.tree.map(np.asarray, self.params),
                "target_params": jax.tree.map(np.asarray,
                                              self.target_params),
                "opt_state": jax.tree.map(np.asarray, self.opt_state),
                "timesteps": self._timesteps,
                "grad_steps": self._grad_steps}

    def load_checkpoint(self, ck):
        self.params = jax.tree.map(jnp.asarray, ck["params"])
        self.target_params = jax.tree.map(jnp.asarray, ck["target_params"])
        self.opt_state = jax.tree.map(jnp.asarray, ck["opt_state"])
        self._timesteps = ck.get("timesteps", 0)
        self._grad_steps = ck.get("grad_steps", 0)
