"""DreamerV1: world-model RL — learn latent dynamics, imagine, act.

Reference capability: rllib/algorithms/dreamer/ (dreamer.py,
dreamer_torch_policy.py:50-147 losses, dreamer_model.py RSSM) — an RSSM
world model (deterministic GRU path + stochastic gaussian latent),
observation/reward decoders, and an actor-critic trained entirely on
imagined latent rollouts with λ-returns, backpropagating through the
learned dynamics.

TPU redesign: the ENTIRE update — posterior scan over the observed
sequence, KL/reconstruction/reward losses, imagination scan over the
horizon (gradients flow through the dynamics), λ-return scan, actor and
critic updates — is ONE jitted program of three nested ``lax.scan``s;
the reference splits this across three torch optimizers and eager
rollouts (dreamer_torch_policy.py:203 three Adam instances — kept, as
three optax partitions inside the same compiled step).  Dense
encoder/decoder (vector observations; the reference's 64×64 conv
encoder is a pixels-specific frontend, dreamer_model.py:23).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rllib.algorithm import Algorithm, AlgorithmConfig


# -- toy latent-dynamics env (convergence workload) -------------------------

class LinearLatentEnv:
    """Hidden linear dynamics observed through a random projection:
    x' = Ax + Ba + ε, obs = Cx, reward = -|x|² - 0.01|a|².  The world
    model must recover the latent to act; an agent that learns it can
    hold |x| near 0."""

    OBS_DIM, LATENT, ACT_DIM = 6, 2, 2
    HORIZON = 64

    def __init__(self, seed: Optional[int] = None):
        r = np.random.RandomState(0)   # fixed dynamics across instances
        # gains sized so rewards stay O(1) per step (Dreamer's losses
        # assume control-suite-scale rewards; huge reward magnitudes
        # swamp the model loss and destabilize imagined returns)
        self.A = np.eye(self.LATENT) * 0.9
        self.B = r.randn(self.LATENT, self.ACT_DIM) * 0.15
        self.C = r.randn(self.OBS_DIM, self.LATENT) * 0.5
        self.rng = np.random.RandomState(seed)
        self.observation_dim = self.OBS_DIM
        self.action_dim = self.ACT_DIM
        self.x = None
        self.t = 0

    def reset(self):
        self.x = (self.rng.randn(self.LATENT) * 0.7).astype(np.float32)
        self.t = 0
        return (self.C @ self.x).astype(np.float32)

    def step(self, action):
        a = np.clip(np.asarray(action, np.float32), -1.0, 1.0)
        noise = self.rng.randn(self.LATENT).astype(np.float32) * 0.01
        self.x = (self.A @ self.x + self.B @ a + noise).astype(np.float32)
        self.t += 1
        reward = float(-(self.x ** 2).sum() - 0.01 * (a ** 2).sum())
        done = self.t >= self.HORIZON
        return (self.C @ self.x).astype(np.float32), reward, done


# -- config -----------------------------------------------------------------

@dataclass
class DreamerConfig(AlgorithmConfig):
    # model sizes (reference defaults scaled to vector obs:
    # dreamer.py DreamerConfig dreamer_model/hidden_size)
    deter_size: int = 64                 # GRU state
    stoch_size: int = 8                  # stochastic latent
    hidden: int = 64                     # MLP width
    # losses (reference dreamer.py: kl_coeff=1.0, free_nats=3.0,
    # lambda=0.95, imagine_horizon=15)
    kl_coeff: float = 1.0
    free_nats: float = 1.0
    lambda_: float = 0.95
    imagine_horizon: int = 10
    gamma: float = 0.99
    # training (reference: td_model_lr/actor_lr/critic_lr + grad_clip)
    model_lr: float = 3e-3
    actor_lr: float = 1e-3
    critic_lr: float = 1e-3
    grad_clip: float = 100.0
    batch_size: int = 16                 # sequences per update
    seq_len: int = 16
    buffer_episodes: int = 200
    prefill_episodes: int = 5
    model_warmup_updates: int = 40       # model-only updates before the
    #                                      actor trains on imagination
    train_iters_per_step: int = 10       # model updates per training_step
    episodes_per_step: int = 2
    explore_noise: float = 0.3

    def build(self, algo_cls=None) -> "Dreamer":
        return Dreamer({"_config": self})


# -- model ------------------------------------------------------------------

def _dense(key, nin, nout, scale=1.0):
    k1, _ = jax.random.split(key)
    lim = scale * np.sqrt(6.0 / (nin + nout))
    return {"w": jax.random.uniform(k1, (nin, nout), jnp.float32,
                                    -lim, lim),
            "b": jnp.zeros((nout,), jnp.float32)}


def _mlp(params, x):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jax.nn.elu(x)
    return x


def init_dreamer_params(cfg: DreamerConfig, obs_dim: int, act_dim: int,
                        rng) -> dict:
    ks = iter(jax.random.split(rng, 24))
    H, D, S = cfg.hidden, cfg.deter_size, cfg.stoch_size
    feat = D + S
    return {
        "encoder": [_dense(next(ks), obs_dim, H), _dense(next(ks), H, H)],
        # GRU cell: input [stoch + action] -> deter
        "gru": {"wi": _dense(next(ks), S + act_dim, 3 * D),
                "wh": _dense(next(ks), D, 3 * D)},
        # prior p(s|h) and posterior q(s|h, embed): mean+std heads
        "prior": [_dense(next(ks), D, H), _dense(next(ks), H, 2 * S)],
        "post": [_dense(next(ks), D + H, H), _dense(next(ks), H, 2 * S)],
        "obs_dec": [_dense(next(ks), feat, H), _dense(next(ks), H, obs_dim)],
        "rew_dec": [_dense(next(ks), feat, H), _dense(next(ks), H, 1)],
        # small-init output head: actions start near tanh(0) instead of
        # saturated, so the world model trains on diverse actions first
        "actor": [_dense(next(ks), feat, H), _dense(next(ks), H, H),
                  _dense(next(ks), H, 2 * act_dim, scale=0.1)],
        "critic": [_dense(next(ks), feat, H), _dense(next(ks), H, 1)],
    }


def _gru(p, x, h):
    """GRU cell; the candidate's hidden contribution passes through the
    reset gate (standard formulation)."""
    xi = x @ p["wi"]["w"] + p["wi"]["b"]
    hh = h @ p["wh"]["w"] + p["wh"]["b"]
    D = h.shape[-1]
    r = jax.nn.sigmoid(xi[..., :D] + hh[..., :D])
    z = jax.nn.sigmoid(xi[..., D:2 * D] + hh[..., D:2 * D])
    n = jnp.tanh(xi[..., 2 * D:] + r * hh[..., 2 * D:])
    return (1 - z) * n + z * h


def _stats(raw):
    S = raw.shape[-1] // 2
    mean, std = raw[..., :S], jax.nn.softplus(raw[..., S:]) + 0.1
    return mean, std


def _img_step(p, stoch, deter, action):
    """Prior step: (s, h, a) -> (h', prior mean/std)."""
    h = _gru(p["gru"], jnp.concatenate([stoch, action], -1), deter)
    mean, std = _stats(_mlp(p["prior"], h))
    return h, mean, std


def _obs_step(p, stoch, deter, action, embed):
    """Posterior step: also condition on the encoded observation."""
    h, pmean, pstd = _img_step(p, stoch, deter, action)
    x = jnp.concatenate([h, embed], -1)
    qmean, qstd = _stats(_mlp(p["post"], x))
    return h, (pmean, pstd), (qmean, qstd)


def _kl(qm, qs, pm, ps):
    return (jnp.log(ps / qs)
            + (qs ** 2 + (qm - pm) ** 2) / (2 * ps ** 2) - 0.5).sum(-1)


def make_dreamer_update(cfg: DreamerConfig, obs_dim: int, act_dim: int,
                        tx_model, tx_actor, tx_critic):
    free_nats = cfg.free_nats
    H = cfg.imagine_horizon

    def observe(p, obs_seq, act_seq, rng):
        """Posterior scan over [B, T, ...]; returns features and KL.

        The transition INTO step t is conditioned on a_{t-1} (the action
        taken at obs_{t-1}) — the same causal filtering policy_step does
        online; buffer actions are stored as taken-AT-obs_t, so they
        shift right by one with a zero first action."""
        B, T = obs_seq.shape[:2]
        embed = _mlp(p["encoder"], obs_seq)              # [B, T, H]
        prev_act = jnp.concatenate(
            [jnp.zeros_like(act_seq[:, :1]), act_seq[:, :-1]], axis=1)

        def step(carry, xs):
            stoch, deter, rng = carry
            a, e = xs
            h, (pm, ps), (qm, qs) = _obs_step(p, stoch, deter, a, e)
            rng, sub = jax.random.split(rng)
            s = qm + qs * jax.random.normal(sub, qm.shape)
            kl = _kl(qm, qs, pm, ps)                     # [B]
            return (s, h, rng), (jnp.concatenate([h, s], -1), kl)

        stoch0 = jnp.zeros((B, cfg.stoch_size))
        deter0 = jnp.zeros((B, cfg.deter_size))
        (_, _, _), (feats, kls) = jax.lax.scan(
            step, (stoch0, deter0, rng),
            (prev_act.transpose(1, 0, 2), embed.transpose(1, 0, 2)))
        return feats, kls                                # [T, B, feat], [T, B]

    def model_loss(p, batch, rng):
        obs, act, rew = batch["obs"], batch["actions"], batch["rewards"]
        feats, kls = observe(p, obs, act, rng)
        obs_t = obs.transpose(1, 0, 2)                   # [T, B, obs]
        rew_t = rew.transpose(1, 0)                      # [T, B]
        obs_pred = _mlp(p["obs_dec"], feats)
        # arrival-reward convention: rew[t-1] (the reward produced by
        # a_{t-1}) is predicted from feat_t — matching imagination, where
        # the decoder reads the arrived-at state
        rew_pred = _mlp(p["rew_dec"], feats[1:])[..., 0]
        # unit-variance gaussian NLL ≡ MSE (reference: image/reward
        # log_prob, dreamer_torch_policy.py:76-77)
        recon = 0.5 * ((obs_pred - obs_t) ** 2).sum(-1).mean()
        rloss = 0.5 * ((rew_pred - rew_t[:-1]) ** 2).mean()
        div = jnp.maximum(kls.mean(), free_nats)
        loss = cfg.kl_coeff * div + recon + rloss
        return loss, (feats, {"model_loss": loss, "obs_loss": recon,
                              "reward_loss": rloss, "kl": kls.mean()})

    def actor_sample(p, feat, rng):
        raw = _mlp(p["actor"], feat)
        mean, std = _stats(raw)
        eps = jax.random.normal(rng, mean.shape)
        return jnp.tanh(mean + std * eps)

    def imagine(p, actor_p, feats0, rng):
        """Imagination rollout from every posterior state, gradients flow
        through the dynamics (Dreamer's defining trick)."""
        stoch = feats0[..., cfg.deter_size:]
        deter = feats0[..., :cfg.deter_size]

        def step(carry, _):
            stoch, deter, rng = carry
            feat = jnp.concatenate([deter, stoch], -1)
            rng, sub1, sub2 = jax.random.split(rng, 3)
            a = actor_sample({"actor": actor_p}, feat, sub1)
            h, pm, ps = _img_step(p, stoch, deter, a)
            s = pm + ps * jax.random.normal(sub2, pm.shape)
            return (s, h, rng), jnp.concatenate([h, s], -1)

        (_, _, _), feats = jax.lax.scan(step, (stoch, deter, rng),
                                        None, length=H)
        return feats                                     # [H, N, feat]

    def lambda_returns(rew, val, gamma, lam):
        """[H, N] λ-returns (reference dreamer_torch_policy.py:101-104)."""
        inputs = rew[:-1] + gamma * val[1:] * (1 - lam)

        def agg(nxt, x):
            y = x + gamma * lam * nxt
            return y, y

        _, rets = jax.lax.scan(agg, val[-1], (inputs)[::-1])
        return rets[::-1]                                # [H-1, N]

    def actor_loss(actor_p, model_p, feats_flat, rng):
        p = {**model_p, "actor": actor_p}
        ifeats = imagine(p, actor_p, feats_flat, rng)    # [H, N, feat]
        rew = _mlp(p["rew_dec"], ifeats)[..., 0]         # [H, N]
        val = _mlp(p["critic"], ifeats)[..., 0]
        rets = lambda_returns(rew, val, cfg.gamma, cfg.lambda_)
        disc = jnp.cumprod(
            jnp.concatenate([jnp.ones((1,)),
                             jnp.full((H - 2,), cfg.gamma)]), 0)
        loss = -(disc[:, None] * rets).mean()
        return loss, (ifeats, rets)

    def critic_loss(critic_p, model_p, ifeats, rets):
        p = {**model_p, "critic": critic_p}
        val = _mlp(p["critic"], ifeats[:-1])[..., 0]
        return 0.5 * ((val - jax.lax.stop_gradient(rets)) ** 2).mean()

    from functools import partial

    @partial(jax.jit, static_argnames=("train_ac",))
    def update(state, batch, rng, train_ac: bool = True):
        params, opt_m, opt_a, opt_c = state
        r1, r2, r3 = jax.random.split(rng, 3)

        model_p = {k: v for k, v in params.items()
                   if k not in ("actor", "critic")}
        (mloss, (feats, metrics)), g_model = jax.value_and_grad(
            model_loss, has_aux=True)(model_p, batch, r1)
        upd_m, opt_m = tx_model.update(g_model, opt_m, model_p)
        model_p = optax.apply_updates(model_p, upd_m)

        if not train_ac:
            # warmup phase: let the world model settle before the actor
            # starts trusting (and exploiting) its imagination
            new_params = {**model_p, "actor": params["actor"],
                          "critic": params["critic"]}
            metrics = {**metrics,
                       "actor_loss": jnp.zeros(()),
                       "critic_loss": jnp.zeros(())}
            return (new_params, opt_m, opt_a, opt_c), metrics

        feats_flat = jax.lax.stop_gradient(
            feats.reshape(-1, feats.shape[-1]))
        full_p = {**model_p, "critic": params["critic"]}
        (aloss, (ifeats, rets)), g_actor = jax.value_and_grad(
            actor_loss, has_aux=True)(params["actor"], full_p,
                                      feats_flat, r2)
        upd_a, opt_a = tx_actor.update(g_actor, opt_a, params["actor"])
        actor_p = optax.apply_updates(params["actor"], upd_a)

        closs, g_critic = jax.value_and_grad(critic_loss)(
            params["critic"], model_p,
            jax.lax.stop_gradient(ifeats), rets)
        upd_c, opt_c = tx_critic.update(g_critic, opt_c, params["critic"])
        critic_p = optax.apply_updates(params["critic"], upd_c)

        new_params = {**model_p, "actor": actor_p, "critic": critic_p}
        metrics = {**metrics, "actor_loss": aloss, "critic_loss": closs}
        return (new_params, opt_m, opt_a, opt_c), metrics

    return update, observe, actor_sample


# -- sequence replay --------------------------------------------------------

class EpisodeBuffer:
    """Whole episodes host-side; samples [B, seq_len] windows."""

    def __init__(self, capacity: int, seed: int = 0):
        self.episodes: list[dict] = []
        self.capacity = capacity
        self.rng = np.random.RandomState(seed)

    def add(self, ep: dict) -> None:
        self.episodes.append(ep)
        if len(self.episodes) > self.capacity:
            self.episodes.pop(0)

    def __len__(self):
        return len(self.episodes)

    def sample(self, batch_size: int, seq_len: int) -> dict:
        outs = {"obs": [], "actions": [], "rewards": []}
        for _ in range(batch_size):
            ep = self.episodes[self.rng.randint(len(self.episodes))]
            T = len(ep["rewards"])
            start = self.rng.randint(max(1, T - seq_len + 1))
            sl = slice(start, start + seq_len)
            for k in outs:
                seq = ep[k][sl]
                if len(seq) < seq_len:   # pad short tails by repetition
                    pad = np.repeat(seq[-1:], seq_len - len(seq), axis=0)
                    seq = np.concatenate([seq, pad], 0)
                outs[k].append(seq)
        return {k: np.stack(v) for k, v in outs.items()}


# -- algorithm --------------------------------------------------------------

class Dreamer(Algorithm):
    _default_config = DreamerConfig

    def _build(self):
        cfg = self.config
        # the base config's env DEFAULT is the discrete CartPole string;
        # Dreamer is continuous-control, so only that inherited default
        # maps to the latent toy env — explicit strings resolve normally
        env = cfg.env
        if isinstance(env, str):
            if env == AlgorithmConfig.env:
                env = LinearLatentEnv
            else:
                from ray_tpu.rllib.env import make_env
                env = make_env(env, seed=cfg.seed)
        self.env = env(seed=cfg.seed) if callable(env) else env
        if not hasattr(self.env, "action_dim"):
            raise ValueError(
                f"Dreamer needs a continuous env exposing action_dim; "
                f"{type(self.env).__name__} does not")
        obs_dim = self.env.observation_dim
        act_dim = getattr(self.env, "action_dim", 1)
        self.act_dim = act_dim
        self.params_rng = jax.random.PRNGKey(cfg.seed)
        params = init_dreamer_params(cfg, obs_dim, act_dim, self.params_rng)
        def tx(lr):
            return optax.chain(optax.clip_by_global_norm(cfg.grad_clip),
                               optax.adam(lr))

        self.tx_model = tx(cfg.model_lr)
        self.tx_actor = tx(cfg.actor_lr)
        self.tx_critic = tx(cfg.critic_lr)
        model_p = {k: v for k, v in params.items()
                   if k not in ("actor", "critic")}
        self.state = (params, self.tx_model.init(model_p),
                      self.tx_actor.init(params["actor"]),
                      self.tx_critic.init(params["critic"]))
        self._update, self._observe, self._actor_sample = \
            make_dreamer_update(cfg, obs_dim, act_dim, self.tx_model,
                                self.tx_actor, self.tx_critic)

        @jax.jit
        def policy_step(params, stoch, deter, prev_action, obs, rng):
            """Online filtering: one posterior step then act."""
            embed = _mlp(params["encoder"], obs)
            h, _, (qm, qs) = _obs_step(params, stoch, deter,
                                       prev_action, embed)
            rng, s1, s2 = jax.random.split(rng, 3)
            s = qm + qs * jax.random.normal(s1, qm.shape)
            feat = jnp.concatenate([h, s], -1)
            raw = _mlp(params["actor"], feat)
            mean, std = _stats(raw)
            a = jnp.tanh(mean + std * jax.random.normal(s2, mean.shape))
            return s, h, a, rng

        self._policy_step = policy_step
        self._rng = jax.random.PRNGKey(cfg.seed + 1)
        self._model_updates = 0
        self.buffer = EpisodeBuffer(cfg.buffer_episodes, seed=cfg.seed)
        for _ in range(cfg.prefill_episodes):
            self._collect_episode(random_policy=True)

    def _collect_episode(self, random_policy: bool = False,
                         explore: bool = True,
                         record: bool = True) -> float:
        cfg = self.config
        obs = self.env.reset()
        stoch = jnp.zeros((1, cfg.stoch_size))
        deter = jnp.zeros((1, cfg.deter_size))
        prev_a = jnp.zeros((1, self.act_dim))
        traj = {"obs": [], "actions": [], "rewards": []}
        ep_rew, done = 0.0, False
        params = self.state[0]
        while not done:
            if random_policy:
                a = np.random.RandomState(
                    int(self._timesteps)).uniform(
                    -1, 1, (self.act_dim,)).astype(np.float32)
            else:
                stoch, deter, a_j, self._rng = self._policy_step(
                    params, stoch, deter, prev_a,
                    jnp.asarray(obs, jnp.float32)[None], self._rng)
                a = np.asarray(a_j)[0]
                if explore and cfg.explore_noise > 0:
                    # exploration noise on the executed action (Dreamer
                    # paper: ε ~ N(0, 0.3)) keeps the replayed action
                    # distribution wide enough that the model can't be
                    # exploited in unvisited action regions
                    a = np.clip(
                        a + np.asarray(
                            jax.random.normal(
                                jax.random.fold_in(
                                    self._rng, self._timesteps),
                                a.shape)) * cfg.explore_noise,
                        -1.0, 1.0).astype(np.float32)
                prev_a = jnp.asarray(a, jnp.float32)[None]
            nobs, rew, done = self.env.step(a)
            traj["obs"].append(np.asarray(obs, np.float32))
            traj["actions"].append(np.asarray(a, np.float32))
            traj["rewards"].append(np.float32(rew))
            obs = nobs
            ep_rew += rew
            if record:
                self._timesteps += 1
        if record:
            self.buffer.add({k: np.stack(v) for k, v in traj.items()})
            self._ep_returns.append(ep_rew)
        return ep_rew

    def training_step(self) -> dict:
        cfg = self.config
        for _ in range(cfg.episodes_per_step):
            self._collect_episode()
        metrics = {}
        for _ in range(cfg.train_iters_per_step):
            b = self.buffer.sample(cfg.batch_size, cfg.seq_len)
            jb = {k: jnp.asarray(v) for k, v in b.items()}
            self._rng, sub = jax.random.split(self._rng)
            train_ac = self._model_updates >= cfg.model_warmup_updates
            self.state, m = self._update(self.state, jb, sub,
                                         train_ac=train_ac)
            self._model_updates += 1
            metrics = {k: float(v) for k, v in m.items()}
        return {"steps_this_iter":
                cfg.episodes_per_step * getattr(self.env, "HORIZON", 64),
                **metrics}

    def evaluate_episodes(self, n: int = 4) -> float:
        """Mean return of noise-free policy episodes (the honest policy
        metric — collection episodes carry exploration noise).  Side-
        effect free: eval episodes enter neither the buffer nor the
        training counters."""
        return float(np.mean(
            [self._collect_episode(explore=False, record=False)
             for _ in range(n)]))

    def save_checkpoint(self) -> dict:
        return {"state": jax.tree.map(np.asarray, self.state),
                "timesteps": self._timesteps,
                "model_updates": self._model_updates}

    def load_checkpoint(self, ck):
        self.state = jax.tree.map(jnp.asarray, ck["state"])
        self._timesteps = ck.get("timesteps", 0)
        # without this a restored agent re-enters the model-only warmup
        self._model_updates = ck.get("model_updates", 0)
