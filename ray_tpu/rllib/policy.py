"""JaxPolicy: actor-critic policy with a compiled action path.

Reference capability: rllib/policy/torch_policy.py:65 TorchPolicy
(compute_actions, loss, multi-GPU towers :495,553).  TPU redesign: the
policy is a pure pytree + jitted functions — no towers: the learner mesh
shards the train step (dp over batch), and rollout workers run the same
compute_actions jitted on CPU.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class PolicyConfig:
    obs_dim: int
    num_actions: int
    hiddens: tuple = (64, 64)


def init_policy_params(cfg: PolicyConfig, rng: jax.Array):
    dims = (cfg.obs_dim, *cfg.hiddens)
    keys = jax.random.split(rng, len(dims) + 1)
    params = {}
    for i in range(len(dims) - 1):
        params[f"fc{i}"] = {
            "w": (jax.random.normal(keys[i], (dims[i], dims[i + 1]))
                  * np.sqrt(2.0 / dims[i])).astype(jnp.float32),
            "b": jnp.zeros((dims[i + 1],), jnp.float32)}
    params["pi"] = {
        "w": (jax.random.normal(keys[-2], (dims[-1], cfg.num_actions))
              * 0.01).astype(jnp.float32),
        "b": jnp.zeros((cfg.num_actions,), jnp.float32)}
    params["vf"] = {
        "w": (jax.random.normal(keys[-1], (dims[-1], 1)) * 1.0
              ).astype(jnp.float32),
        "b": jnp.zeros((1,), jnp.float32)}
    return params


def policy_forward(params, obs):
    """obs [B, obs_dim] → (logits [B, A], value [B])."""
    x = obs
    i = 0
    while f"fc{i}" in params:
        lp = params[f"fc{i}"]
        x = jnp.tanh(x @ lp["w"] + lp["b"])
        i += 1
    logits = x @ params["pi"]["w"] + params["pi"]["b"]
    value = (x @ params["vf"]["w"] + params["vf"]["b"])[:, 0]
    return logits, value


class JaxPolicy:
    """Holds params + jitted sample/value functions."""

    def __init__(self, cfg: PolicyConfig, seed: int = 0):
        self.cfg = cfg
        self.params = init_policy_params(cfg, jax.random.PRNGKey(seed))
        self._rng = jax.random.PRNGKey(seed + 1)

        @jax.jit
        def _act(params, rng, obs):
            logits, value = policy_forward(params, obs)
            rng, sub = jax.random.split(rng)
            actions = jax.random.categorical(sub, logits, axis=-1)
            logp = jax.nn.log_softmax(logits)[
                jnp.arange(logits.shape[0]), actions]
            return rng, actions, logp, value, logits

        self._act = _act

    def compute_actions(self, obs: np.ndarray):
        """(reference: TorchPolicy.compute_actions) → actions, logp, vf."""
        self._rng, actions, logp, value, logits = self._act(
            self.params, self._rng, jnp.asarray(obs))
        return (np.asarray(actions), np.asarray(logp), np.asarray(value),
                np.asarray(logits))

    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = jax.tree.map(jnp.asarray, weights)


def compute_gae(rewards, values, dones, last_value, *, gamma=0.99,
                lam=0.95):
    """Generalized advantage estimation over a [T, B] rollout
    (reference: rllib/evaluation/postprocessing.py compute_advantages)."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last_gae = np.zeros_like(last_value)
    next_value = last_value
    for t in reversed(range(T)):
        nonterminal = 1.0 - dones[t].astype(np.float32)
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_value = values[t]
    value_targets = adv + values
    return adv, value_targets
